"""HA-HDFS machinery tested with zero Hadoop, mirroring the reference's mock
strategy (hdfs/tests/test_hdfs_namenode.py:43-341): a fake Hadoop configuration,
a fake filesystem that fails its first N operations, and a connector that counts
connection attempts."""

import pickle

import pytest

from petastorm_tpu.hdfs.namenode import (HadoopConfiguration, HAHdfsClient,
                                         HdfsConnectError, HdfsConnector,
                                         HdfsNamenodeResolver, MaxFailoversExceeded,
                                         resolve_and_connect)

HDFS_SITE = """<?xml version="1.0"?>
<configuration>
  <property><name>dfs.ha.namenodes.nameservice1</name><value>nn1,nn2</value></property>
  <property><name>dfs.namenode.rpc-address.nameservice1.nn1</name><value>host1:8020</value></property>
  <property><name>dfs.namenode.rpc-address.nameservice1.nn2</name><value>host2:8020</value></property>
</configuration>
"""

CORE_SITE = """<?xml version="1.0"?>
<configuration>
  <property><name>fs.defaultFS</name><value>hdfs://nameservice1</value></property>
</configuration>
"""


@pytest.fixture
def hadoop_conf(tmp_path):
    (tmp_path / 'hdfs-site.xml').write_text(HDFS_SITE)
    (tmp_path / 'core-site.xml').write_text(CORE_SITE)
    conf = HadoopConfiguration()
    conf.load_site_xml(str(tmp_path / 'hdfs-site.xml'))
    conf.load_site_xml(str(tmp_path / 'core-site.xml'))
    return conf


class MockHdfs(object):
    """Filesystem stub failing its first ``n_failures`` operations
    (reference MockHdfs, hdfs/tests/test_hdfs_namenode.py:250-292)."""

    def __init__(self, n_failures=0, namenode=None):
        self._n_failures = n_failures
        self.namenode = namenode
        self.calls = 0

    def ls(self, path):
        self.calls += 1
        if self._n_failures > 0:
            self._n_failures -= 1
            raise OSError('namenode is in standby state')
        return ['{}/{}'.format(path, 'part-0.parquet')]

    def bad_method(self):
        raise ValueError('not an IO error')


class MockHdfsConnector(HdfsConnector):
    """Counts connections; serves preprogrammed MockHdfs instances per namenode
    (reference MockHdfsConnector, hdfs/tests/test_hdfs_namenode.py:294-341)."""

    connect_attempts = {}
    fail_n_next_connects = 0
    instances = {}

    @classmethod
    def reset(cls):
        cls.connect_attempts = {}
        cls.fail_n_next_connects = 0
        cls.instances = {}

    @classmethod
    def set_fs(cls, namenode, fs):
        cls.instances[namenode] = fs

    @classmethod
    def hdfs_connect_namenode(cls, url_or_address, user=None):
        cls.connect_attempts[url_or_address] = cls.connect_attempts.get(url_or_address, 0) + 1
        if cls.fail_n_next_connects > 0:
            cls.fail_n_next_connects -= 1
            raise OSError('connection refused: {}'.format(url_or_address))
        return cls.instances.get(url_or_address, MockHdfs(namenode=url_or_address))


@pytest.fixture(autouse=True)
def _reset_connector():
    MockHdfsConnector.reset()
    yield
    MockHdfsConnector.reset()


# -- configuration & resolution ------------------------------------------------

def test_site_xml_parsing(hadoop_conf):
    assert hadoop_conf['dfs.ha.namenodes.nameservice1'] == 'nn1,nn2'
    assert hadoop_conf['fs.defaultFS'] == 'hdfs://nameservice1'


def test_site_xml_parse_error_is_nonfatal(tmp_path):
    bad = tmp_path / 'broken.xml'
    bad.write_text('<configuration><property>')
    conf = HadoopConfiguration()
    conf.load_site_xml(str(bad))  # logs, does not raise
    assert conf == {}


def test_resolve_nameservice(hadoop_conf):
    resolver = HdfsNamenodeResolver(hadoop_conf)
    assert resolver.resolve_hdfs_name_service('nameservice1') == ['host1:8020', 'host2:8020']


def test_resolve_unknown_nameservice_returns_none(hadoop_conf):
    assert HdfsNamenodeResolver(hadoop_conf).resolve_hdfs_name_service('some-host') is None


def test_resolve_inconsistent_config_raises(hadoop_conf):
    del hadoop_conf['dfs.namenode.rpc-address.nameservice1.nn2']
    with pytest.raises(RuntimeError, match='nn2'):
        HdfsNamenodeResolver(hadoop_conf).resolve_hdfs_name_service('nameservice1')


def test_resolve_default_service(hadoop_conf):
    nameservice, namenodes = HdfsNamenodeResolver(hadoop_conf).resolve_default_hdfs_service()
    assert nameservice == 'nameservice1'
    assert namenodes == ['host1:8020', 'host2:8020']


def test_resolve_default_service_without_config():
    with pytest.raises(RuntimeError, match='fs.defaultFS'):
        HdfsNamenodeResolver(HadoopConfiguration()).resolve_default_hdfs_service()


# -- connector -----------------------------------------------------------------

def test_connect_to_either_namenode_prefers_first():
    fs = MockHdfsConnector.connect_to_either_namenode(['host1:8020', 'host2:8020'])
    assert fs.namenode == 'host1:8020'
    assert MockHdfsConnector.connect_attempts == {'host1:8020': 1}


def test_connect_to_either_namenode_fails_over():
    MockHdfsConnector.fail_n_next_connects = 1
    fs = MockHdfsConnector.connect_to_either_namenode(['host1:8020', 'host2:8020'])
    assert fs.namenode == 'host2:8020'
    assert MockHdfsConnector.connect_attempts == {'host1:8020': 1, 'host2:8020': 1}


def test_connect_to_either_namenode_all_down():
    MockHdfsConnector.fail_n_next_connects = 2
    with pytest.raises(HdfsConnectError):
        MockHdfsConnector.connect_to_either_namenode(['host1:8020', 'host2:8020'])


# -- HA client failover --------------------------------------------------------

def _ha_client(n_failures):
    # one shared filesystem stub failing the first N operations wherever they
    # land, as in the reference's MockHdfs (test_hdfs_namenode.py:250-292)
    shared = MockHdfs(n_failures=n_failures, namenode='host1:8020')
    MockHdfsConnector.set_fs('host1:8020', shared)
    MockHdfsConnector.set_fs('host2:8020', shared)
    return HAHdfsClient(MockHdfsConnector, ['host1:8020', 'host2:8020'])


def test_ha_client_no_failure():
    client = _ha_client(0)
    assert client.ls('/data') == ['/data/part-0.parquet']
    assert MockHdfsConnector.connect_attempts == {'host1:8020': 1}


@pytest.mark.parametrize('n_failures', [1, 2])
def test_ha_client_recovers_within_failover_budget(n_failures):
    client = _ha_client(n_failures)
    assert client.ls('/data') == ['/data/part-0.parquet']
    # every failure reconnects round-robin to the next namenode
    assert sum(MockHdfsConnector.connect_attempts.values()) == 1 + n_failures


def test_ha_client_exceeds_failover_budget():
    # 3 failures > MAX_FAILOVER_ATTEMPTS=2: round-robin returns to the (still
    # broken) first namenode and gives up
    MockHdfsConnector.set_fs('host1:8020', MockHdfs(n_failures=5, namenode='host1:8020'))
    MockHdfsConnector.set_fs('host2:8020', MockHdfs(n_failures=5, namenode='host2:8020'))
    client = HAHdfsClient(MockHdfsConnector, ['host1:8020', 'host2:8020'])
    with pytest.raises(MaxFailoversExceeded) as exc_info:
        client.ls('/data')
    assert len(exc_info.value.failed_exceptions) == 3
    assert exc_info.value.__name__ == 'ls'


def test_ha_client_non_io_error_propagates_immediately():
    client = _ha_client(0)
    with pytest.raises(ValueError, match='not an IO error'):
        client.bad_method()
    assert sum(MockHdfsConnector.connect_attempts.values()) == 1  # no failover


def test_ha_client_non_callable_attribute_proxy():
    client = _ha_client(0)
    assert client.namenode == 'host1:8020'


def test_ha_client_failure_names_failed_operation():
    MockHdfsConnector.set_fs('host1:8020', MockHdfs(n_failures=5))
    MockHdfsConnector.set_fs('host2:8020', MockHdfs(n_failures=5))
    client = HAHdfsClient(MockHdfsConnector, ['host1:8020', 'host2:8020'])
    with pytest.raises(MaxFailoversExceeded) as exc_info:
        client.ls('/data')
    assert exc_info.value.__name__ == 'ls'


def test_ha_client_requires_namenodes():
    with pytest.raises(HdfsConnectError):
        HAHdfsClient(MockHdfsConnector, [])


def test_ha_client_pickle_reconnects():
    client = _ha_client(0)
    restored = pickle.loads(pickle.dumps(client))
    assert restored.ls('/d') == ['/d/part-0.parquet']


# -- URL resolution ------------------------------------------------------------

def test_resolve_and_connect_nameservice(hadoop_conf):
    fs, path = resolve_and_connect('hdfs://nameservice1/datasets/d1',
                                   hadoop_configuration=hadoop_conf,
                                   connector=MockHdfsConnector)
    assert isinstance(fs, HAHdfsClient)
    assert path == '/datasets/d1'
    assert fs.ls('/datasets/d1')


def test_resolve_and_connect_default_service(hadoop_conf):
    fs, path = resolve_and_connect('hdfs:///datasets/d1',
                                   hadoop_configuration=hadoop_conf,
                                   connector=MockHdfsConnector)
    assert isinstance(fs, HAHdfsClient)
    assert path == '/datasets/d1'


def test_resolve_and_connect_direct_host(hadoop_conf):
    fs, path = resolve_and_connect('hdfs://some-host:8020/datasets/d1',
                                   hadoop_configuration=hadoop_conf,
                                   connector=MockHdfsConnector)
    assert not isinstance(fs, HAHdfsClient)
    assert fs.namenode == 'some-host:8020'
    assert path == '/datasets/d1'


def test_resolve_and_connect_rejects_non_hdfs():
    with pytest.raises(ValueError):
        resolve_and_connect('file:///tmp/x')


def test_ha_client_initial_connect_skips_down_namenode():
    # first-listed namenode refuses connections: the client must come up on
    # the standby instead of failing resolution outright
    MockHdfsConnector.fail_n_next_connects = 1
    client = HAHdfsClient(MockHdfsConnector, ['host1:8020', 'host2:8020'])
    assert client.ls('/x') == ['/x/part-0.parquet']
    assert MockHdfsConnector.connect_attempts == {'host1:8020': 1, 'host2:8020': 1}


def test_ha_client_reconnect_failure_terminal_when_ring_down():
    from petastorm_tpu.hdfs.namenode import HdfsConnectError as ConnErr
    # operation fails, and during failover every namenode refuses connections
    MockHdfsConnector.set_fs('host1:8020', MockHdfs(n_failures=5))
    MockHdfsConnector.set_fs('host2:8020', MockHdfs(n_failures=5))
    client = HAHdfsClient(MockHdfsConnector, ['host1:8020', 'host2:8020'])
    MockHdfsConnector.fail_n_next_connects = 10
    with pytest.raises(ConnErr):
        client.ls('/x')


def test_resolve_and_connect_mixed_case_nameservice(hadoop_conf):
    # Hadoop config keys are case-sensitive; urlparse().hostname lowercases —
    # the resolver must use the case-preserved netloc host
    hadoop_conf['dfs.ha.namenodes.NameService1'] = 'nn1,nn2'
    hadoop_conf['dfs.namenode.rpc-address.NameService1.nn1'] = 'host1:8020'
    hadoop_conf['dfs.namenode.rpc-address.NameService1.nn2'] = 'host2:8020'
    fs, path = resolve_and_connect('hdfs://NameService1/data',
                                   hadoop_configuration=hadoop_conf,
                                   connector=MockHdfsConnector)
    assert isinstance(fs, HAHdfsClient)


def test_resolve_and_connect_ipv6_literal(hadoop_conf):
    # bracketed IPv6 netloc must resolve as a direct host, not nameservice '['
    fs, path = resolve_and_connect('hdfs://[::1]:8020/data',
                                   hadoop_configuration=hadoop_conf,
                                   connector=MockHdfsConnector)
    assert not isinstance(fs, HAHdfsClient)
    assert path == '/data'


def test_resolve_and_connect_userinfo(hadoop_conf):
    fs, _ = resolve_and_connect('hdfs://alice@nameservice1/data',
                                hadoop_configuration=hadoop_conf,
                                connector=MockHdfsConnector)
    assert fs._user == 'alice'


def test_connector_parses_userinfo():
    captured = {}

    class RecordingConnector(MockHdfsConnector):
        @classmethod
        def hdfs_connect_namenode(cls, url_or_address, user=None):
            from urllib.parse import urlparse
            parsed = urlparse('hdfs://' + url_or_address)
            captured['user'] = user or parsed.username
            return MockHdfs(namenode=url_or_address)

    RecordingConnector.hdfs_connect_namenode('bob@host1:8020')
    assert captured['user'] == 'bob'


def test_as_pyarrow_filesystem_accepted_by_pyarrow(tmp_path):
    """The HA wrapper must be a *real* pyarrow FileSystem so strict pyarrow
    APIs (pq.write_to_dataset/_ensure_filesystem) accept it."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.fs as pafs
    import pyarrow.parquet as pq

    from petastorm_tpu.hdfs.namenode import as_pyarrow_filesystem

    class LocalBackedConnector(HdfsConnector):
        @classmethod
        def hdfs_connect_namenode(cls, url_or_address, user=None):
            return pafs.LocalFileSystem()

    client = HAHdfsClient(LocalBackedConnector, ['host1:8020', 'host2:8020'])
    fs = as_pyarrow_filesystem(client)
    assert isinstance(fs, pafs.FileSystem)

    table = pa.table({'id': np.arange(10)})
    out = str(tmp_path / 'ha_out')
    pq.write_to_dataset(table, out, filesystem=fs)
    files = [f.path for f in fs.get_file_info(pafs.FileSelector(out, recursive=True))
             if f.type == pafs.FileType.File]
    assert files
    assert pq.read_table(files[0], filesystem=fs)['id'].to_pylist() == list(range(10))
