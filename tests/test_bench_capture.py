"""Capture hardening of the driver-facing bench entry point (bench.py):
the duty-sweep subprocess streamer and the contention-aware run filter.
These mechanisms decide the number of record, so they get their own tests."""

import json
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, __file__.rsplit('/tests/', 1)[0])

import bench  # noqa: E402


def _fake_sweep_cmd(body):
    return [sys.executable, '-c', textwrap.dedent(body)]


def test_stream_duty_sweep_captures_burst(capsys):
    """Complete lines flushed in ONE burst must all be captured — the
    buffered-readline implementation lost all but the first (they sat in the
    TextIOWrapper buffer where select can't see them)."""
    cmd = _fake_sweep_cmd("""
        import json, sys
        lines = [json.dumps({'metric': 'duty_sweep', 'model': 'm%d' % i,
                             'input_stall_fraction': 0.1 * i}) for i in range(4)]
        sys.stdout.write('\\n'.join(lines) + '\\n')
        sys.stdout.flush()
    """)
    points, error = bench._stream_duty_sweep(30, cmd=cmd)
    assert error is None
    assert [p['model'] for p in points] == ['m0', 'm1', 'm2', 'm3']
    out = [json.loads(ln) for ln in capsys.readouterr().out.strip().splitlines()]
    assert [p['model'] for p in out] == ['m0', 'm1', 'm2', 'm3']


def test_stream_duty_sweep_deadline_keeps_completed_points():
    """A sweep that hangs mid-ladder is killed at the deadline with every
    completed point retained and the partial state recorded."""
    # 20s deadline: interpreter startup alone can take several seconds on the
    # contended 1-core bench host, and the child must get its points out
    # before the kill for the salvage assertion to mean anything
    cmd = _fake_sweep_cmd("""
        import json, sys, time
        for i in range(2):
            print(json.dumps({'metric': 'duty_sweep', 'model': 'm%d' % i,
                              'input_stall_fraction': 0.5}), flush=True)
        time.sleep(600)
    """)
    points, error = bench._stream_duty_sweep(20, cmd=cmd)
    assert len(points) == 2
    assert 'deadline' in error and '2 points' in error


def test_stream_duty_sweep_reports_child_failure_with_stderr_tail():
    cmd = _fake_sweep_cmd("""
        import sys
        sys.stderr.write('RuntimeError: tunnel fell over\\n')
        sys.exit(3)
    """)
    points, error = bench._stream_duty_sweep(30, cmd=cmd)
    assert points == []
    assert 'rc=3' in error and 'tunnel fell over' in error


def test_stream_duty_sweep_survives_chatty_stderr():
    """>64 KiB of stderr (a chatty TPU runtime) must not deadlock the sweep —
    stderr goes to a temp file, not an undrained pipe."""
    cmd = _fake_sweep_cmd("""
        import json, sys
        sys.stderr.write('x' * 200_000)
        sys.stderr.flush()
        print(json.dumps({'metric': 'duty_sweep', 'model': 'm',
                          'input_stall_fraction': 0.2}), flush=True)
    """)
    points, error = bench._stream_duty_sweep(30, cmd=cmd)
    assert error is None
    assert len(points) == 1


def test_main_emits_headline_line(monkeypatch, capsys):
    """main()'s JSON assembly runs end-to-end with stubbed measurement — a
    NameError in the final print would otherwise only surface in the driver's
    once-per-round capture, losing the round's number."""
    import types

    import petastorm_tpu.tools.throughput as tp

    monkeypatch.setattr(bench, '_prebuild_native', lambda: None)
    monkeypatch.setattr(bench, '_ensure_dataset', lambda url: None)
    monkeypatch.setattr(bench, '_warm', lambda url: None)
    monkeypatch.setattr(bench, '_duty_section',
                        lambda: {'skipped': True, 'reason': 'stubbed'})
    monkeypatch.setattr(bench, '_spin_ms', lambda: 250.0)
    monkeypatch.setattr(tp, 'reader_throughput',
                        lambda *a, **k: types.SimpleNamespace(samples_per_second=5000.0))
    bench.main()
    lines = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(lines[-1])
    assert rec['metric'] == 'hello_world_reader_throughput'
    assert rec['value'] == 5000.0
    assert len(rec['runs']) == 7 and len(rec['cpu_shares']) == 7
    assert len(rec['spin_ms']) == 7 and rec['host_speed_spread'] == 0.0
    assert rec['spread'] == 0.0 and rec['excluded_mad_outliers'] == []
    assert rec['duty'] == {'skipped': True, 'reason': 'stubbed'}


def test_select_runs_excludes_contended():
    """A run whose CPU share shows it lost the core is excluded from the
    median (the BENCH_r04 bimodality: two of five runs ~10% low)."""
    runs = [(5600.0, 0.98), (5000.0, 0.86), (5650.0, 0.97),
            (5580.0, 0.975), (5610.0, 0.98), (5590.0, 0.97), (5620.0, 0.96)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert excluded == [5000.0]
    assert mad_excluded == []
    assert value == pytest.approx(5605.0)  # median of the 6 clean runs
    assert spread < 0.02 < spread_all


def test_select_runs_mad_outlier_excluded():
    """A share-clean run far off the cluster (host-speed dip mid-run) is a
    MAD outlier: excluded from the median WITH the exclusion on record."""
    runs = [(5600.0, 0.98), (5650.0, 0.97), (4300.0, 0.975),  # dip, clean share
            (5580.0, 0.975), (5610.0, 0.98), (5590.0, 0.97), (5620.0, 0.96)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert excluded == []
    assert mad_excluded == [4300.0]
    assert value == pytest.approx(5605.0)
    assert spread < 0.02
    assert spread_all == pytest.approx((5650.0 - 4300.0) / 5600.0, rel=1e-3)


def test_select_runs_zero_dispersion_keeps_all():
    """mad == 0 (near-identical runs) means no dispersion — the filter must
    not treat it as infinite confidence and evict the one run that differs by
    a hundredth (review r5 regression)."""
    runs = [(5000.0, 0.98)] * 6 + [(5000.01, 0.98)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert mad_excluded == [] and excluded == []
    assert value == pytest.approx(5000.0)
    assert spread == pytest.approx(spread_all)


def test_select_runs_contended_capture_reports_all():
    """Fewer than 4 clean runs -> no filtering: the whole capture was
    contended and the report must say so rather than cherry-pick."""
    runs = [(5600.0, 0.98), (5000.0, 0.80), (4900.0, 0.79),
            (4800.0, 0.81), (5100.0, 0.82), (4950.0, 0.80), (5050.0, 0.83)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert excluded == [] and mad_excluded == []
    assert value == pytest.approx(5000.0)
    assert spread == spread_all
