"""Capture hardening of the driver-facing bench entry point (bench.py):
the duty-sweep subprocess streamer and the contention-aware run filter.
These mechanisms decide the number of record, so they get their own tests."""

import json
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, __file__.rsplit('/tests/', 1)[0])

import bench  # noqa: E402


def _fake_sweep_cmd(body):
    return [sys.executable, '-c', textwrap.dedent(body)]


def test_stream_duty_sweep_captures_burst(capsys):
    """Complete lines flushed in ONE burst must all be captured — the
    buffered-readline implementation lost all but the first (they sat in the
    TextIOWrapper buffer where select can't see them)."""
    cmd = _fake_sweep_cmd("""
        import json, sys
        lines = [json.dumps({'metric': 'duty_sweep', 'model': 'm%d' % i,
                             'input_stall_fraction': 0.1 * i}) for i in range(4)]
        sys.stdout.write('\\n'.join(lines) + '\\n')
        sys.stdout.flush()
    """)
    points, error = bench._stream_duty_sweep(30, cmd=cmd)
    assert error is None
    assert [p['model'] for p in points] == ['m0', 'm1', 'm2', 'm3']
    out = [json.loads(ln) for ln in capsys.readouterr().out.strip().splitlines()]
    assert [p['model'] for p in out] == ['m0', 'm1', 'm2', 'm3']


def test_stream_duty_sweep_deadline_keeps_completed_points():
    """A sweep that hangs mid-ladder is killed at the deadline with every
    completed point retained and the partial state recorded."""
    # 20s deadline: interpreter startup alone can take several seconds on the
    # contended 1-core bench host, and the child must get its points out
    # before the kill for the salvage assertion to mean anything
    cmd = _fake_sweep_cmd("""
        import json, sys, time
        for i in range(2):
            print(json.dumps({'metric': 'duty_sweep', 'model': 'm%d' % i,
                              'input_stall_fraction': 0.5}), flush=True)
        time.sleep(600)
    """)
    points, error = bench._stream_duty_sweep(20, cmd=cmd)
    assert len(points) == 2
    assert 'deadline' in error and '2 points' in error


def test_stream_duty_sweep_reports_child_failure_with_stderr_tail():
    cmd = _fake_sweep_cmd("""
        import sys
        sys.stderr.write('RuntimeError: tunnel fell over\\n')
        sys.exit(3)
    """)
    points, error = bench._stream_duty_sweep(30, cmd=cmd)
    assert points == []
    assert 'rc=3' in error and 'tunnel fell over' in error


def test_stream_duty_sweep_survives_chatty_stderr():
    """>64 KiB of stderr (a chatty TPU runtime) must not deadlock the sweep —
    stderr goes to a temp file, not an undrained pipe."""
    cmd = _fake_sweep_cmd("""
        import json, sys
        sys.stderr.write('x' * 200_000)
        sys.stderr.flush()
        print(json.dumps({'metric': 'duty_sweep', 'model': 'm',
                          'input_stall_fraction': 0.2}), flush=True)
    """)
    points, error = bench._stream_duty_sweep(30, cmd=cmd)
    assert error is None
    assert len(points) == 1


def test_main_emits_headline_line(monkeypatch, capsys):
    """main()'s JSON assembly runs end-to-end with stubbed measurement — a
    NameError in the final print would otherwise only surface in the driver's
    once-per-round capture, losing the round's number."""
    import types

    import petastorm_tpu.tools.throughput as tp

    monkeypatch.setattr(bench, '_probe_tpu', lambda *a, **k: ('none', 0))
    monkeypatch.setattr(bench, '_prebuild_native', lambda: None)
    monkeypatch.setattr(bench, '_ensure_dataset', lambda url, **kw: None)
    monkeypatch.setattr(bench, '_warm', lambda url: None)
    monkeypatch.setattr(bench, '_duty_section',
                        lambda **kw: {'skipped': True, 'reason': 'stubbed'})
    monkeypatch.setattr(bench, '_spin_ms', lambda: 250.0)
    monkeypatch.setattr(tp, 'reader_throughput',
                        lambda *a, **k: types.SimpleNamespace(samples_per_second=5000.0))
    bench.main()
    lines = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(lines[-1])
    assert rec['metric'] == 'hello_world_reader_throughput'
    assert rec['value'] == 5000.0
    # identical runs on an identical-speed host: normalized == raw
    assert rec['value_spin_normalized'] == 5000.0
    assert len(rec['runs']) == 7 and len(rec['cpu_shares']) == 7
    assert len(rec['spin_ms']) == 7 and rec['host_speed_spread'] == 0.0
    assert rec['spread'] == 0.0 and rec['excluded_mad_outliers'] == []
    assert rec['duty'] == {'skipped': True, 'reason': 'stubbed'}
    # default capture runs at counters level: no critical-path block
    assert rec['critical_path'] is None
    # compression knob defaults: snappy store, sweep only on request, and the
    # predicate-share key is always present so round-over-round diffs line up
    assert rec['compression'] == 'snappy'
    assert rec['compression_sweep'] is None
    assert 'fused_predicate_share' in rec


def test_critical_path_section_spans_level():
    """At spans level the headline embeds the causal-tracing summary; below
    it the block stays None (no half-filled attributions)."""
    from petastorm_tpu import observability as obs
    saved = obs.current_config()
    obs.configure('spans')
    try:
        obs.get_ring().clear()
        with obs.mint_trace('feedc0de', 3):
            with obs.stage('ventilate', cat='ventilator'):
                pass
        section = bench._critical_path_section('spans')
        assert section['traced_batches'] == 1
        assert section['slowest'][0]['trace'] == 'feedc0de:3'
        assert bench._critical_path_section('counters') is None
        assert bench._critical_path_section(None) is None
    finally:
        obs.configure(saved)
        obs.get_ring().clear()


def test_select_runs_excludes_contended():
    """A run whose CPU share shows it lost the core is excluded from the
    median (the BENCH_r04 bimodality: two of five runs ~10% low)."""
    runs = [(5600.0, 0.98), (5000.0, 0.86), (5650.0, 0.97),
            (5580.0, 0.975), (5610.0, 0.98), (5590.0, 0.97), (5620.0, 0.96)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert excluded == [5000.0]
    assert mad_excluded == []
    assert value == pytest.approx(5605.0)  # median of the 6 clean runs
    assert spread < 0.02 < spread_all


def test_select_runs_mad_outlier_excluded():
    """A share-clean run far off the cluster (host-speed dip mid-run) is a
    MAD outlier: excluded from the median WITH the exclusion on record."""
    runs = [(5600.0, 0.98), (5650.0, 0.97), (4300.0, 0.975),  # dip, clean share
            (5580.0, 0.975), (5610.0, 0.98), (5590.0, 0.97), (5620.0, 0.96)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert excluded == []
    assert mad_excluded == [4300.0]
    assert value == pytest.approx(5605.0)
    assert spread < 0.02
    assert spread_all == pytest.approx((5650.0 - 4300.0) / 5600.0, rel=1e-3)


def test_select_runs_zero_dispersion_keeps_all():
    """mad == 0 (near-identical runs) means no dispersion — the filter must
    not treat it as infinite confidence and evict the one run that differs by
    a hundredth (review r5 regression)."""
    runs = [(5000.0, 0.98)] * 6 + [(5000.01, 0.98)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert mad_excluded == [] and excluded == []
    assert value == pytest.approx(5000.0)
    assert spread == pytest.approx(spread_all)


def test_select_runs_contended_capture_reports_all():
    """Fewer than 4 clean runs -> no filtering: the whole capture was
    contended and the report must say so rather than cherry-pick."""
    runs = [(5600.0, 0.98), (5000.0, 0.80), (4900.0, 0.79),
            (4800.0, 0.81), (5100.0, 0.82), (4950.0, 0.80), (5050.0, 0.83)]
    value, spread, spread_all, excluded, mad_excluded = bench._select_runs(runs)
    assert excluded == [] and mad_excluded == []
    assert value == pytest.approx(5000.0)
    assert spread == spread_all


def test_fused_predicate_share():
    """The headline's predicate-share metric: pred batches over all fused
    batches; None when nothing fused (no fabricated 0.0 from a dead capture)."""
    assert bench._fused_predicate_share({}) is None
    assert bench._fused_predicate_share({'fused_batches_total': 8}) == 0.0
    assert bench._fused_predicate_share(
        {'fused_batches_total': 8, 'fused_pred_batches_total': 2}) == 0.25


# ---------------------------------------------------------------------------
# Spin-normalized headline (the CPU-wander remedy)
# ---------------------------------------------------------------------------

def test_spin_normalization_cancels_host_speed_wander():
    """A run that is 20% slow ONLY because the host was 20% slow (spin probe
    20% higher) normalizes back to the cluster: rate × spin / median(spin)."""
    rates = [5000.0, 5000.0, 5000.0 / 1.2, 5000.0, 5000.0]
    spins = [250.0, 250.0, 250.0 * 1.2, 250.0, 250.0]
    norm = bench._spin_normalized(rates, spins)
    assert norm == pytest.approx(5000.0)
    # raw median is also 5000 here, but the slow run's NORMALIZED value is
    # exactly restored — verify the per-run formula directly
    per_run = [r * s / 250.0 for r, s in zip(rates, spins)]
    assert per_run[2] == pytest.approx(5000.0)


def test_spin_normalization_uniform_host_is_identity():
    rates = [4000.0, 4100.0, 4200.0]
    spins = [300.0, 300.0, 300.0]
    assert bench._spin_normalized(rates, spins) == pytest.approx(4100.0)


def test_spin_normalization_degenerate_inputs():
    assert bench._spin_normalized([], []) is None
    assert bench._spin_normalized([1.0], [1.0, 2.0]) is None
    # zero spins (clock glitch): fall back to the raw median, not a crash
    assert bench._spin_normalized([10.0, 20.0, 30.0], [0.0, 0.0, 0.0]) == 20.0


# ---------------------------------------------------------------------------
# Persistent on-chip ledger (BENCH_ONCHIP.json)
# ---------------------------------------------------------------------------

def _use_tmp_ledger(monkeypatch, tmp_path):
    path = str(tmp_path / 'BENCH_ONCHIP.json')
    monkeypatch.setattr(bench, 'ONCHIP_PATH', path)
    return path


def test_onchip_record_and_latest_roundtrip(monkeypatch, tmp_path):
    _use_tmp_ledger(monkeypatch, tmp_path)
    assert bench._latest_onchip() is None
    bench._record_onchip({'model': 'resnet152', 'step_ms': 210.0,
                          'input_stall_fraction': 0.031, 'duty_cycle': 0.969,
                          'examples_per_sec': 301.0, 'device': 'tpu'})
    last = bench._latest_onchip()
    assert last['model'] == 'resnet152'
    assert last['recorded_utc'].endswith('Z')
    assert last['age_days'] is not None and last['age_days'] < 1.0


def test_onchip_ledger_bounded_and_ordered(monkeypatch, tmp_path):
    _use_tmp_ledger(monkeypatch, tmp_path)
    for i in range(25):
        bench._record_onchip({'model': 'm{}'.format(i), 'examples_per_sec': float(i)})
    doc = bench._load_onchip()
    assert len(doc['entries']) == 20  # bounded history
    assert bench._latest_onchip()['model'] == 'm24'  # newest last


def test_onchip_corrupt_ledger_recovers(monkeypatch, tmp_path):
    path = _use_tmp_ledger(monkeypatch, tmp_path)
    with open(path, 'w') as f:
        f.write('not json{')
    assert bench._load_onchip() == {'entries': []}
    bench._record_onchip({'model': 'm'})
    assert bench._latest_onchip()['model'] == 'm'


def test_duty_skip_line_embeds_age_stamped_onchip(monkeypatch, tmp_path, capsys):
    """A TPU-less capture must still carry the newest committed on-chip
    number, age-stamped, in its skip line."""
    _use_tmp_ledger(monkeypatch, tmp_path)
    bench._record_onchip({'model': 'resnet101', 'input_stall_fraction': 0.042,
                          'examples_per_sec': 412.5, 'device': 'tpu'})
    monkeypatch.setattr(bench, '_probe_tpu', lambda *a, **k: ('cpu', 1))
    duty = bench._duty_section()
    out = [json.loads(ln) for ln in capsys.readouterr().out.strip().splitlines()]
    skip = [r for r in out if r.get('metric') == 'duty_sweep_skipped'][0]
    assert skip['last_onchip']['model'] == 'resnet101'
    assert skip['last_onchip']['age_days'] is not None
    assert duty['skipped'] is True
    assert duty['last_onchip']['examples_per_sec'] == 412.5


def test_duty_section_sweeps_when_tpu_seen_early(monkeypatch, tmp_path, capsys):
    """A TPU seen by the START-of-capture probe must trigger the sweep even
    if the end-of-capture probe misses (opportunistic probing), and a
    successful sweep must persist to the ledger."""
    _use_tmp_ledger(monkeypatch, tmp_path)
    monkeypatch.setattr(bench, '_probe_tpu', lambda *a, **k: ('none', 0))
    point = {'metric': 'duty_sweep', 'model': 'resnet50', 'step_ms': 80.0,
             'input_stall_fraction': 0.02, 'duty_cycle': 0.98,
             'examples_per_sec': 800.0}
    monkeypatch.setattr(bench, '_stream_duty_sweep',
                        lambda *a, **k: ([point], None))
    duty = bench._duty_section(tpu_seen_early=True)
    assert duty['model'] == 'resnet50' and duty['meets_bar'] is True
    last = bench._latest_onchip()
    assert last['model'] == 'resnet50' and last['age_days'] is not None
    # and WITHOUT the early sighting, the same probes skip
    duty2 = bench._duty_section(tpu_seen_early=False)
    assert duty2['skipped'] is True
    assert duty2['last_onchip']['model'] == 'resnet50'
