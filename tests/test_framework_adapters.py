"""Torch and TF adapter parity tests (modeled on reference tests/test_pytorch_dataloader.py
and tests/test_tf_utils.py — kept light since JAX is the primary interface)."""

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader

FIXED_FIELDS = ['id', 'matrix', 'decimal']


class TestTorchDataLoader:
    def test_batches(self, synthetic_dataset):
        import torch
        from petastorm_tpu.torch_utils import DataLoader
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=FIXED_FIELDS, shuffle_row_groups=False) as reader:
            batches = list(DataLoader(reader, batch_size=30))
        assert len(batches) == 4  # partial final batch kept (parity w/ reference)
        assert isinstance(batches[0]['matrix'], torch.Tensor)
        assert batches[0]['matrix'].shape == (30, 32, 16, 3)
        assert batches[0]['decimal'].dtype == torch.float64
        assert len(batches[-1]['id']) == 10

    def test_uint16_promoted(self, synthetic_dataset):
        import torch
        from petastorm_tpu.torch_utils import DataLoader
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id', 'matrix_uint16'],
                         shuffle_row_groups=False) as reader:
            batch = next(iter(DataLoader(reader, batch_size=4)))
        assert batch['matrix_uint16'].dtype == torch.int32

    def test_string_field_rejected(self, synthetic_dataset):
        from petastorm_tpu.torch_utils import DataLoader
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id', 'sensor_name'],
                         shuffle_row_groups=False) as reader:
            with pytest.raises(TypeError, match='TransformSpec'):
                next(iter(DataLoader(reader, batch_size=4)))

    def test_shuffling(self, synthetic_dataset):
        from petastorm_tpu.torch_utils import DataLoader
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id'], shuffle_row_groups=False) as reader:
            ids = []
            for b in DataLoader(reader, batch_size=10, shuffling_queue_capacity=40, seed=5):
                ids.extend(b['id'].tolist())
        assert sorted(ids) == list(range(100))
        assert ids != sorted(ids)


class TestTfDataset:
    def test_make_petastorm_dataset(self, synthetic_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id', 'matrix'], shuffle_row_groups=False) as reader:
            ds = make_petastorm_dataset(reader)
            rows = list(ds.take(5))
        assert len(rows) == 5
        assert rows[0].matrix.shape == (32, 16, 3)
        assert int(rows[0].id) == 0

    def test_tf1_session_migration_recipe(self, synthetic_dataset):
        """The documented tf_tensors replacement (PARITY.md §2.6, ref
        tf_utils.py:289-338) must actually run: a TF1 ``Session`` pulling
        tensors per ``session.run`` from
        ``tf.compat.v1.data.make_one_shot_iterator(make_petastorm_dataset(r))``,
        including the shuffle the reference's RandomShuffleQueue provided."""
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id', 'matrix'], shuffle_row_groups=False,
                         num_epochs=1) as reader:
            with tf.Graph().as_default():
                ds = make_petastorm_dataset(reader, shuffle_buffer_size=20, seed=3)
                readout = tf.compat.v1.data.make_one_shot_iterator(ds).get_next()
                ids = []
                with tf.compat.v1.Session() as sess:
                    while True:
                        try:
                            row = sess.run(readout)
                        except tf.errors.OutOfRangeError:
                            break
                        ids.append(int(row.id))
                        assert row.matrix.shape == (32, 16, 3)
        assert sorted(ids) == list(range(100))  # every row, exactly once
        assert ids != sorted(ids)  # the queue-style shuffle actually shuffled

    def test_batched_reader_dataset(self, scalar_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               schema_fields=['id', 'float64'],
                               shuffle_row_groups=False) as reader:
            ds = make_petastorm_dataset(reader)
            batch = next(iter(ds))
        assert batch.id.shape[0] == 10  # row-group sized

    def test_dtype_promotions(self, synthetic_dataset):
        # uint16 -> int32, Decimal -> string (reference tf_utils.py:27-44)
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id', 'matrix_uint16', 'decimal'],
                         shuffle_row_groups=False) as reader:
            row = next(iter(make_petastorm_dataset(reader)))
        assert row.matrix_uint16.dtype == tf.int32
        assert row.decimal.dtype == tf.string
        assert row.decimal.numpy().decode().startswith('0.')

    def test_ngram_flattening(self, synthetic_dataset):
        # NGram windows surface as dicts of offset -> per-timestep namedtuples
        # (reference tf_utils.py:141-183,254-286)
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.ngram import NGram
        from petastorm_tpu.test_util.dataset_utils import TestSchema
        from petastorm_tpu.tf_utils import make_petastorm_dataset

        ngram = NGram({0: [TestSchema.id, TestSchema.id2], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            windows = list(make_petastorm_dataset(reader).take(8))
        assert sorted(windows[0].keys()) == [0, 1]
        assert set(windows[0][0]._fields) == {'id', 'id2'}
        assert set(windows[0][1]._fields) == {'id'}
        for w in windows:
            assert int(w[1].id) == int(w[0].id) + 1

    def test_ngram_with_images_through_tf(self, synthetic_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.ngram import NGram
        from petastorm_tpu.test_util.dataset_utils import TestSchema
        from petastorm_tpu.tf_utils import make_petastorm_dataset

        ngram = NGram({0: [TestSchema.id, TestSchema.image_png], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            w = next(iter(make_petastorm_dataset(reader)))
        expected = {r['id']: r for r in synthetic_dataset.data}
        np.testing.assert_array_equal(w[0].image_png.numpy(),
                                      expected[int(w[0].id)]['image_png'])

    def test_shuffle_buffer(self, synthetic_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id'], shuffle_row_groups=False) as reader:
            ids = [int(r.id) for r in make_petastorm_dataset(
                reader, shuffle_buffer_size=40, seed=3)]
        assert sorted(ids) == list(range(100))
        assert ids != sorted(ids)  # decorrelated

    def test_shuffle_buffer_seed_reproducible(self, synthetic_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset

        def run():
            with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                             schema_fields=['id'], shuffle_row_groups=False) as reader:
                return [int(r.id) for r in make_petastorm_dataset(
                    reader, shuffle_buffer_size=40, seed=11)]

        assert run() == run()

    def test_shuffle_rejected_for_batched_reader(self, scalar_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               schema_fields=['id'], shuffle_row_groups=False) as reader:
            with pytest.raises(ValueError, match='batched reader'):
                make_petastorm_dataset(reader, shuffle_buffer_size=10)


class TestTorchColumnarFastPath:
    """Round 3: block fast path for columnar readers under the default collate."""

    def test_columnar_matches_row_path_values(self, synthetic_dataset):
        from petastorm_tpu import make_reader
        from petastorm_tpu.torch_utils import DataLoader
        fields = ['id', 'matrix', 'decimal']
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=fields, shuffle_row_groups=False) as reader:
            row_batches = list(DataLoader(reader, batch_size=20))
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                         schema_fields=fields, shuffle_row_groups=False) as reader:
            loader = DataLoader(reader, batch_size=20)
            assert loader._columnar
            col_batches = list(loader)
        assert len(row_batches) == len(col_batches)
        for rb, cb in zip(row_batches, col_batches):
            for k in rb:
                np.testing.assert_array_equal(rb[k].numpy(), cb[k].numpy())
                assert rb[k].dtype == cb[k].dtype

    def test_columnar_shuffled_covers_all_rows(self, scalar_dataset):
        import torch
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.torch_utils import DataLoader
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               schema_fields=['id', 'float64'],
                               shuffle_row_groups=False) as reader:
            loader = DataLoader(reader, batch_size=16, shuffling_queue_capacity=40, seed=3)
            ids = torch.cat([b['id'] for b in loader])
        assert sorted(ids.tolist()) == list(range(100))

    def test_custom_collate_keeps_row_path(self, scalar_dataset):
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.torch_utils import DataLoader

        def my_collate(rows):
            return len(rows)

        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               schema_fields=['id'], shuffle_row_groups=False) as reader:
            loader = DataLoader(reader, batch_size=25, collate_fn=my_collate)
            assert not loader._columnar
            assert list(loader) == [25, 25, 25, 25]

    def test_readonly_columns_copied_for_torch(self):
        import torch
        from petastorm_tpu.torch_utils import _collate_columns_to_torch
        col = np.arange(6, dtype=np.int64)
        col.setflags(write=False)
        out = _collate_columns_to_torch({'x': col})
        out['x'][0] = 99  # writable: a copy was made, source untouched
        assert col[0] == 0 and out['x'][0] == 99
        assert isinstance(out['x'], torch.Tensor)


class TestBackgroundPrefetch:
    def test_background_prefetch_yields_all_and_stages(self, synthetic_dataset):
        import jax
        from petastorm_tpu import make_reader
        from petastorm_tpu.jax import JaxDataLoader, prefetch_to_device
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                         schema_fields=['id'], shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=10, drop_last=False)
            batches = list(prefetch_to_device(iter(loader), jax.devices()[0], size=2))
        assert sum(len(b['id']) for b in batches) == 100
        assert all(isinstance(b['id'], jax.Array) for b in batches)

    def test_background_prefetch_propagates_errors(self):
        import jax
        from petastorm_tpu.jax import prefetch_to_device

        def boom():
            yield {'x': np.ones(2, np.float32)}
            raise RuntimeError('pipeline exploded')

        it = prefetch_to_device(boom(), jax.devices()[0], size=2)
        next(it)
        with pytest.raises(RuntimeError, match='pipeline exploded'):
            next(it)

    def test_background_prefetch_early_abandon_stops_thread(self):
        import itertools
        import threading
        import jax
        from petastorm_tpu.jax import prefetch_to_device

        def infinite():
            for i in itertools.count():
                yield {'x': np.full(4, i, np.float32)}

        before = threading.active_count()
        it = prefetch_to_device(infinite(), jax.devices()[0], size=2)
        next(it)
        it.close()  # GeneratorExit -> stop event -> pump thread joins
        import time
        deadline = time.monotonic() + 5
        while threading.active_count() > before and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not any(t.name == 'pstpu-prefetch' and t.is_alive()
                       for t in threading.enumerate())

    def test_synchronous_mode_still_works(self, synthetic_dataset):
        import jax
        from petastorm_tpu import make_reader
        from petastorm_tpu.jax import JaxDataLoader, prefetch_to_device
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                         schema_fields=['id'], shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=10, drop_last=False)
            batches = list(prefetch_to_device(iter(loader), jax.devices()[0], size=2,
                                              background=False))
        assert sum(len(b['id']) for b in batches) == 100


def test_torch_columnar_datetime_promoted(scalar_dataset):
    """Regression: datetime columns (object or 'M' dtype) through the torch
    columnar fast path come out as int64 ns tensors, like the row path."""
    import torch
    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.torch_utils import DataLoader
    from petastorm_tpu.test_util.dataset_utils import create_scalar_dataset  # noqa: F401
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           schema_fields=['id', 'datetime'],
                           shuffle_row_groups=False) as reader:
        loader = DataLoader(reader, batch_size=20)
        assert loader._columnar
        batch = next(iter(loader))
    assert batch['datetime'].dtype == torch.int64
    assert batch['datetime'].shape == (20,)


def test_loader_state_dict_safe_under_background_prefetch(synthetic_dataset):
    """Regression: state_dict() from the training thread while the background
    prefetch pump iterates the loader must neither crash nor lose rows."""
    import jax
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import JaxDataLoader, prefetch_to_device
    reader = make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         output='columnar', schema_fields=['id'],
                         shuffle_row_groups=False, seed=5, num_epochs=None)
    loader = JaxDataLoader(reader, batch_size=7, shuffling_queue_capacity=30, seed=5)
    it = prefetch_to_device(iter(loader), jax.devices()[0], size=2)
    states = []
    for i in range(6):
        next(it)
        states.append(loader.state_dict())  # concurrent with the pump thread
    it.close()
    reader.stop(); reader.join()
    for s in states:
        assert s['version'] == 1 and isinstance(s['rows'], list)
