"""Reader end-to-end matrix (modeled on reference tests/test_end_to_end.py).

Factories are parametrized over pool types: MINIMAL (dummy only, fast) for
semantics tests, ALL (thread + dummy) for pipeline tests; process pool gets a
dedicated smoke test (spawn cost is high).
"""

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader, TransformSpec
from petastorm_tpu.errors import NoDataAvailableError, PetastormTpuError
from petastorm_tpu.predicates import in_lambda, in_pseudorandom_split, in_reduce, in_set
from petastorm_tpu.selectors import IntersectIndexSelector, SingleIndexSelector, UnionIndexSelector
from petastorm_tpu.test_util.dataset_utils import TestSchema

MINIMAL_FACTORIES = [
    lambda url, **kw: make_reader(url, reader_pool_type='dummy', **kw),
]
ALL_FACTORIES = [
    lambda url, **kw: make_reader(url, reader_pool_type='dummy', **kw),
    lambda url, **kw: make_reader(url, reader_pool_type='thread', workers_count=3, **kw),
]
ALL_IDS = ['dummy', 'thread']


def _readout_all(reader):
    return {row.id: row for row in reader}


@pytest.mark.parametrize('factory', ALL_FACTORIES, ids=ALL_IDS)
def test_simple_read_all_rows(synthetic_dataset, factory):
    with factory(synthetic_dataset.url) as reader:
        rows = _readout_all(reader)
    assert len(rows) == 100
    expected = {r['id']: r for r in synthetic_dataset.data}
    for i in (0, 17, 99):
        np.testing.assert_array_equal(rows[i].image_png, expected[i]['image_png'])
        np.testing.assert_array_almost_equal(rows[i].matrix, expected[i]['matrix'])
        assert rows[i].partition_key == expected[i]['partition_key']


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_nullable_fields_roundtrip(synthetic_dataset, factory):
    with factory(synthetic_dataset.url) as reader:
        rows = _readout_all(reader)
    for r in synthetic_dataset.data:
        got = rows[r['id']]
        if r['matrix_nullable'] is None:
            assert got.matrix_nullable is None
        else:
            np.testing.assert_array_equal(got.matrix_nullable, r['matrix_nullable'])


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_schema_fields_subset_and_regex(synthetic_dataset, factory):
    with factory(synthetic_dataset.url, schema_fields=['id$', 'matrix_.*']) as reader:
        row = next(reader)
    fields = set(row._fields)
    assert 'id' in fields
    assert 'matrix_uint16' in fields
    assert 'image_png' not in fields
    assert 'id2' not in fields


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_predicate_on_scalar_field(synthetic_dataset, factory):
    with factory(synthetic_dataset.url, predicate=in_set({3, 7, 77}, 'id')) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == [3, 7, 77]


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_predicate_on_partition_key(synthetic_dataset, factory):
    with factory(synthetic_dataset.url, predicate=in_lambda(
            ['partition_key'], lambda v: v['partition_key'] == 'p_2')) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == [i for i in range(100) if i % 10 == 2]


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_predicate_composition(synthetic_dataset, factory):
    pred = in_reduce([in_set(set(range(0, 50)), 'id'),
                      in_lambda(['id_odd'], lambda v: bool(v['id_odd']))], all)
    with factory(synthetic_dataset.url, predicate=pred) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == [i for i in range(50) if i % 2 == 1]


def test_pseudorandom_split_partitions_disjoint(synthetic_dataset):
    all_ids = []
    for subset in range(2):
        pred = in_pseudorandom_split([0.5, 0.5], subset, 'id')
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         predicate=pred) as reader:
            all_ids.append({row.id for row in reader})
    assert all_ids[0] | all_ids[1] == set(range(100))
    assert not (all_ids[0] & all_ids[1])
    assert 20 <= len(all_ids[0]) <= 80  # roughly balanced


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_transform_spec(synthetic_dataset, factory):
    def double_matrix(row):
        row['matrix'] = row['matrix'] * 2
        return row

    spec = TransformSpec(double_matrix)
    with factory(synthetic_dataset.url, transform_spec=spec,
                 schema_fields=['id', 'matrix']) as reader:
        rows = _readout_all(reader)
    expected = {r['id']: r for r in synthetic_dataset.data}
    np.testing.assert_array_almost_equal(rows[5].matrix, expected[5]['matrix'] * 2)


@pytest.mark.parametrize('factory', MINIMAL_FACTORIES)
def test_transform_spec_removes_and_adds_fields(synthetic_dataset, factory):
    def make_label(row):
        row['label'] = np.int64(row['id'] % 2)
        del row['matrix']
        return row

    spec = TransformSpec(make_label,
                         edit_fields=[('label', np.int64, (), False)],
                         removed_fields=['matrix'])
    with factory(synthetic_dataset.url, transform_spec=spec,
                 schema_fields=['id', 'matrix']) as reader:
        row = next(reader)
    assert set(row._fields) == {'id', 'label'}


def test_shuffle_decorrelates(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     shuffle_row_groups=False, schema_fields=['id']) as reader:
        ordered = [row.id for row in reader]
    assert ordered == sorted(ordered)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     shuffle_row_groups=True, seed=3, schema_fields=['id']) as reader:
        shuffled = [row.id for row in reader]
    assert shuffled != ordered
    assert sorted(shuffled) == ordered


def test_seeded_shuffle_reproducible(synthetic_dataset):
    orders = []
    for _ in range(2):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         shuffle_row_groups=True, seed=11, schema_fields=['id']) as reader:
            orders.append([row.id for row in reader])
    assert orders[0] == orders[1]


def test_shuffle_row_drop_partitions(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     shuffle_row_drop_partitions=3, shuffle_row_groups=False,
                     schema_fields=['id']) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(100))  # every row exactly once across partitions


def test_num_epochs(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', num_epochs=3,
                     shuffle_row_groups=False, schema_fields=['id']) as reader:
        ids = [row.id for row in reader]
    assert len(ids) == 300
    assert sorted(ids) == sorted(list(range(100)) * 3)


def test_reset_rereads(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     shuffle_row_groups=False, schema_fields=['id']) as reader:
        first = [row.id for row in reader]
        reader.reset()
        second = [row.id for row in reader]
    assert first == second == list(range(100))


def test_reset_mid_epoch_raises(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='thread', workers_count=2,
                     schema_fields=['id']) as reader:
        next(reader)
        with pytest.raises(PetastormTpuError):
            reader.reset()


def test_sharding_unions_to_full_dataset(synthetic_dataset):
    """Instantiate one reader per shard in-process and union ids
    (the reference's multi-node-without-a-cluster pattern, :426-448)."""
    all_ids = []
    for shard in range(3):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         cur_shard=shard, shard_count=3, shuffle_row_groups=False,
                         schema_fields=['id']) as reader:
            all_ids.append([row.id for row in reader])
    union = sorted(i for ids in all_ids for i in ids)
    assert union == list(range(100))  # disjoint cover
    assert all(ids for ids in all_ids)


def test_sharding_too_many_shards_raises(synthetic_dataset):
    with pytest.raises(NoDataAvailableError):
        make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                    cur_shard=11, shard_count=12)


def test_rowgroup_selector_single(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     rowgroup_selector=SingleIndexSelector('id_index', [5, 95]),
                     schema_fields=['id']) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(10)) + list(range(90, 100))  # the 2 selected row groups


def test_rowgroup_selector_intersect(synthetic_dataset):
    # sensor index covers all groups; id 5 only group 0 -> intersection = group 0
    sel = IntersectIndexSelector([SingleIndexSelector('id_index', [5]),
                                  SingleIndexSelector('sensor_name_index', ['sensor_1'])])
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     rowgroup_selector=sel, schema_fields=['id']) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(10))


def test_rowgroup_selector_empty_intersection_raises(synthetic_dataset):
    sel = IntersectIndexSelector([SingleIndexSelector('id_index', [5]),
                                  SingleIndexSelector('id_index', [15])])
    with pytest.raises(NoDataAvailableError):
        make_reader(synthetic_dataset.url, reader_pool_type='dummy', rowgroup_selector=sel)


def test_rowgroup_selector_union(synthetic_dataset):
    sel_a = SingleIndexSelector('id_index', [5])
    sel_b = SingleIndexSelector('id_index', [15])
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     rowgroup_selector=UnionIndexSelector([sel_a, sel_b]),
                     schema_fields=['id']) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(20))


def test_unknown_index_raises(synthetic_dataset):
    with pytest.raises(PetastormTpuError):
        make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                    rowgroup_selector=SingleIndexSelector('nope', [1]))


def test_local_disk_cache(synthetic_dataset, tmp_path):
    for _ in range(2):  # second run hits the cache
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         cache_type='local-disk', cache_location=str(tmp_path / 'cache'),
                         shuffle_row_groups=False, schema_fields=['id']) as reader:
            ids = [row.id for row in reader]
        assert ids == list(range(100))
    cache_files = list((tmp_path / 'cache').rglob('*.pkl'))
    assert cache_files  # entries were written


def test_process_pool_reader_smoke(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='process', workers_count=2,
                     schema_fields=['id', 'matrix']) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(100))


def _process_pool_make_label(row):
    row['label'] = np.int64(row['id'] % 2)
    del row['matrix']
    return row


@pytest.mark.slow
class TestProcessPoolEndToEnd:
    """The e2e matrix through the process pool: spawn + zmq control +
    shm-ring/blob results transport + NumpyBlockSerializer (the reference runs
    its full matrix over its process pool too, tests/test_end_to_end.py:37-54).
    A smoke test cannot catch serializer or transport semantics drift in
    decode, predicates, transforms, NGram, or epoch accounting — these do.
    Each test pays a spawn, hence the slow marker."""

    def _reader(self, url, **kw):
        return make_reader(url, reader_pool_type='process', workers_count=2, **kw)

    def test_decode_all_fields(self, synthetic_dataset):
        with self._reader(synthetic_dataset.url) as reader:
            rows = _readout_all(reader)
        assert len(rows) == 100
        expected = {r['id']: r for r in synthetic_dataset.data}
        for i in (0, 42, 99):
            np.testing.assert_array_equal(rows[i].image_png, expected[i]['image_png'])
            np.testing.assert_array_almost_equal(rows[i].matrix, expected[i]['matrix'])
            assert rows[i].decimal == expected[i]['decimal']
        # nullable + ragged fields survive the process boundary
        for r in synthetic_dataset.data:
            got = rows[r['id']]
            if r['matrix_nullable'] is None:
                assert got.matrix_nullable is None
            else:
                np.testing.assert_array_equal(got.matrix_nullable, r['matrix_nullable'])

    def test_predicate_pushdown(self, synthetic_dataset):
        with self._reader(synthetic_dataset.url,
                          predicate=in_set({3, 7, 77}, 'id')) as reader:
            ids = sorted(row.id for row in reader)
        assert ids == [3, 7, 77]

    def test_transform_spec_removes_and_adds_fields(self, synthetic_dataset):
        # module-level fn: spawn pickles the setup blob (no dill by design,
        # PARITY #21), so a process-pool transform must be importable
        spec = TransformSpec(_process_pool_make_label,
                             edit_fields=[('label', np.int64, (), False)],
                             removed_fields=['matrix'])
        with self._reader(synthetic_dataset.url, transform_spec=spec,
                          schema_fields=['id', 'matrix']) as reader:
            rows = _readout_all(reader)
        assert len(rows) == 100
        assert all(set(r._fields) == {'id', 'label'} for r in rows.values())
        assert all(r.label == r.id % 2 for r in rows.values())

    def test_ngram_windows(self, synthetic_dataset):
        from petastorm_tpu.ngram import NGram
        ngram = NGram({0: [TestSchema.id, TestSchema.id2], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        with self._reader(synthetic_dataset.url, ngram=ngram,
                          shuffle_row_groups=False) as reader:
            windows = list(reader)
        # windows are consecutive-id pairs; every eligible start id appears
        assert all(w[1].id == w[0].id + 1 for w in windows)
        assert sorted(w[0].id for w in windows) == \
            sorted(i for i in range(100) if i % 10 <= 8)

    def test_multiple_epochs(self, synthetic_dataset):
        with self._reader(synthetic_dataset.url, num_epochs=3,
                          schema_fields=['id']) as reader:
            ids = [row.id for row in reader]
        assert len(ids) == 300
        assert sorted(set(ids)) == list(range(100))

    def test_batch_reader_columnar_path(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='process',
                               workers_count=2) as reader:
            seen = []
            for batch in reader:
                seen.extend(batch.id.tolist())
                assert batch.float64.dtype == np.float64
        assert sorted(seen) == list(range(100))


def test_make_reader_on_plain_parquet_raises(scalar_dataset):
    with pytest.raises(PetastormTpuError, match='make_batch_reader'):
        make_reader(scalar_dataset.url)


# ---------------------------------------------------------------------------
# make_batch_reader (columnar path)
# ---------------------------------------------------------------------------

def test_batch_reader_reads_all(scalar_dataset):
    seen = []
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           shuffle_row_groups=False) as reader:
        for batch in reader:
            assert reader.batched_output
            seen.extend(batch.id.tolist())
            assert batch.float64.dtype == np.float64
            assert batch.int_fixed_size_list.shape[1] == 3
    assert sorted(seen) == list(range(100))


def test_batch_reader_thread_pool(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='thread',
                           workers_count=3) as reader:
        total = sum(len(b.id) for b in reader)
    assert total == 100


def test_batch_reader_predicate(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           predicate=in_lambda(['id'], lambda v: v['id'] % 10 == 0)) as reader:
        ids = sorted(i for b in reader for i in b.id.tolist())
    assert ids == [0, 10, 20, 30, 40, 50, 60, 70, 80, 90]


def test_batch_reader_transform(scalar_dataset):
    def scale(batch):
        batch['float64'] = batch['float64'] * 10
        return batch

    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           transform_spec=TransformSpec(scale),
                           shuffle_row_groups=False) as reader:
        batch = next(reader)
    np.testing.assert_almost_equal(batch.float64[1], 0.66 * 10)


def test_batch_reader_on_petastorm_dataset_reads_raw(synthetic_dataset):
    """make_batch_reader over a petastorm dataset yields raw (encoded) columns."""
    with make_batch_reader(synthetic_dataset.url, reader_pool_type='dummy',
                           schema_fields=['id', 'image_png'],
                           shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert batch.image_png.dtype == object  # still png bytes, not decoded
    assert isinstance(batch.image_png[0], bytes)


def test_batch_reader_strings_and_datetimes(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert batch.string[1] == 'hello_1'
    assert np.issubdtype(batch.datetime.dtype, np.datetime64)


def test_selector_with_predicate_uses_original_indexes(synthetic_dataset):
    """Selector index sets refer to the unfiltered piece enumeration even when a
    predicate is present (regression: selector ran after predicate filtering)."""
    pred = in_lambda(['id_odd'], lambda v: True)  # worker predicate, keeps all
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', predicate=pred,
                     rowgroup_selector=SingleIndexSelector('id_index', [95]),
                     schema_fields=['id', 'id_odd']) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(90, 100))


def test_dummy_pool_worker_exception_propagates(synthetic_dataset):
    def boom(row):
        raise RuntimeError('transform exploded')

    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     transform_spec=TransformSpec(boom), schema_fields=['id']) as reader:
        with pytest.raises(RuntimeError, match='transform exploded'):
            next(reader)


def test_thread_pool_worker_exception_propagates(synthetic_dataset):
    def boom(row):
        raise RuntimeError('transform exploded')

    with make_reader(synthetic_dataset.url, reader_pool_type='thread', workers_count=2,
                     transform_spec=TransformSpec(boom), schema_fields=['id']) as reader:
        with pytest.raises(RuntimeError, match='transform exploded'):
            for _ in reader:
                pass


def test_batch_reader_predicate_on_excluded_column(scalar_dataset):
    """Predicate column not in schema_fields is read separately and not emitted."""
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           schema_fields=['string'],
                           predicate=in_lambda(['id'], lambda v: v['id'] < 10),
                           shuffle_row_groups=False) as reader:
        batches = list(reader)
    assert sum(len(b.string) for b in batches) == 10
    assert all(set(b._fields) == {'string'} for b in batches)


def test_batch_reader_null_strings_preserved(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    from petastorm_tpu.fs import path_to_url
    path = tmp_path / 'nulls'
    path.mkdir()
    pq.write_table(pa.table({'s': ['a', None, 'c'], 'id': [0, 1, 2]}),
                   str(path / 'f.parquet'))
    with make_batch_reader(path_to_url(path), reader_pool_type='dummy') as reader:
        batch = next(reader)
    assert batch.s[0] == 'a' and batch.s[1] is None and batch.s[2] == 'c'


def test_ngram_no_overlap_with_row_drop_rejected(synthetic_dataset):
    from petastorm_tpu.ngram import NGram
    ngram = NGram({0: [TestSchema.id], 1: [TestSchema.id]}, delta_threshold=1,
                  timestamp_field=TestSchema.id, timestamp_overlap=False)
    with pytest.raises(NotImplementedError):
        make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                    shuffle_row_drop_partitions=2)
