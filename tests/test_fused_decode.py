"""Fused native read→decode→collate (native/fused.py + pstpu_read_fused).

Pins the tentpole contracts of the fused batch path:

* **bit-exact parity** with the Arrow path across every supported physical
  type (INT32/INT64/FLOAT/DOUBLE/FLBA), PLAIN and dictionary/RLE encodings,
  UNCOMPRESSED and SNAPPY chunks, proven-null-free nullable chunks, np.save
  (NdarrayCodec) cells and image-codec columns;
* **one GIL transition per batch** on the fully-fused path (counted via an
  instrumented stub around the single ctypes entry point);
* **loud, labelled fallbacks** — every disqualified column gets a reason
  counter (incl. the ``_MAX_PAGES`` page-cap edge, which used to fall back
  silently);
* **robustness** — seeded (and hypothesis-gated, when available) fuzz of the
  page-header/RLE/snappy parsers: truncated/malformed/adversarial bytes must
  return the error sentinel, never crash or over-read;
* the **shm-ring in-place mode**: reserve/commit/abort semantics, pad-marker
  wrapping, and an end-to-end process-pool read whose batches are assembled
  directly in the ring slots.
"""

import ctypes
import os
import struct

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.codecs import (CompressedImageCodec, NdarrayCodec, RawTensorCodec,
                                  ScalarCodec)
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
from petastorm_tpu.predicates import in_negate, in_range, in_reduce, in_set
from petastorm_tpu.unischema import Unischema, UnischemaField

native = pytest.importorskip('petastorm_tpu.native')
from petastorm_tpu.native import fused, pagescan  # noqa: E402

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason='native kernel unavailable')


def _counters():
    return obs.snapshot().get('counters', {})


def _parquet_path(root):
    return str(next(p for p in root.iterdir() if p.suffix == '.parquet'))


# ---------------------------------------------------------------------------
# parity: fixed-width scalars, every physical type, PLAIN + dictionary,
# UNCOMPRESSED + SNAPPY
# ---------------------------------------------------------------------------

_SCALAR_DTYPES = (np.int32, np.int64, np.float32, np.float64)


def _scalar_schema():
    return Unischema('S', [
        UnischemaField('c_{}'.format(np.dtype(dt).name), dt, (), ScalarCodec(dt), False)
        for dt in _SCALAR_DTYPES])


def _write_scalar_store(tmp_path, compression, repeated):
    """``repeated`` makes values low-cardinality so the dictionary encoder
    keeps the chunk dict-encoded with long RLE runs; unique-ish values give
    bit-packed index groups — both hybrid flavors get exercised."""
    schema = _scalar_schema()
    url = 'file://' + str(tmp_path / 'store')
    rows = []
    for i in range(64):
        v = (i % 4) if repeated else i * 7 + 1
        rows.append({'c_{}'.format(np.dtype(dt).name): np.dtype(dt).type(v)
                     for dt in _SCALAR_DTYPES})
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=16,
                            compression=compression)
    return url, schema, rows


@pytest.mark.parametrize('compression', ['snappy', 'zstd', 'lz4', 'none'])
@pytest.mark.parametrize('repeated', [True, False], ids=['rle-runs', 'bit-packed'])
def test_scalar_parity_all_types(tmp_path, compression, repeated):
    url, schema, rows = _write_scalar_store(tmp_path, compression, repeated)
    path = _parquet_path(tmp_path / 'store')
    md = pq.read_metadata(path)
    # the writer dictionary-encodes scalar columns of non-raw stores
    assert md.row_group(0).column(0).has_dictionary_page
    pf = native.NativeParquetFile(path)
    cols = list(schema.fields)
    for rg in range(md.num_row_groups):
        block, rest = pf.read_fused(rg, cols, schema.fields)
        if compression == 'none' and not md.row_group(rg).column(0).has_dictionary_page:
            continue  # plain uncompressed chunks stay with the view path
        assert rest == [], rest
        table = pf.read_row_group(rg, columns=cols)
        for name in cols:
            ref = table.column(name).to_numpy()
            assert block[name].dtype == np.dtype(schema.fields[name].numpy_dtype)
            np.testing.assert_array_equal(block[name], ref)


@pytest.mark.parametrize('compression', ['snappy', 'zstd', 'lz4', 'none'])
@pytest.mark.parametrize('dictionary', [True, False], ids=['dict', 'plain'])
def test_data_page_v2_parity(tmp_path, compression, dictionary):
    """DATA_PAGE_V2 chunks (previously a blanket ``fused_fallback_reason:
    page-type``) decode through the fused kernel bit-exactly: the v2 header's
    explicit level lengths are skipped, and compression scoped to the data
    region alone is honored. Uncompressed PLAIN v2 stays with the default
    plan's pagescan routing (Arrow serves it), like its v1 twin."""
    n = 200
    table = pa.table({
        'i32': pa.array(np.arange(n, dtype=np.int32)),
        'i64': pa.array(np.arange(n, dtype=np.int64) * 7),
        'f64': pa.array(np.linspace(-5, 5, n)),
        'opt': pa.array(np.arange(n, dtype=np.int64)),  # nullable, zero nulls
    })
    path = str(tmp_path / 'v2.parquet')
    pq.write_table(table, path, data_page_version='2.0',
                   compression=None if compression == 'none' else compression,
                   use_dictionary=dictionary, data_page_size=512,
                   write_statistics=True)
    pf = native.NativeParquetFile(path)
    block, rest = pf.read_fused(0, list(table.column_names), {})
    if compression == 'none' and not dictionary:
        assert block == {}  # pagescan-routed; below proves Arrow parity anyway
    else:
        assert sorted(block) == sorted(table.column_names), (sorted(block), rest)
    for name in block:
        np.testing.assert_array_equal(block[name],
                                      table.column(name).to_numpy(),
                                      err_msg=name)
    # end-to-end: the batch reader serves identical values either way
    from petastorm_tpu import make_batch_reader
    with make_batch_reader('file://' + str(tmp_path), shuffle_row_groups=False,
                           reader_pool_type='dummy') as reader:
        got = np.concatenate([b.i64 for b in reader])
    np.testing.assert_array_equal(np.sort(got), np.arange(n, dtype=np.int64) * 7)


def test_data_page_v2_handwritten_decodes():
    """The handwritten v2 thrift builder round-trips through the fused
    kernel, including a non-empty def-levels prefix skipped by its explicit
    length (num_nulls == 0 proves it carries no information)."""
    levels = b'\x03\x01\x01'  # 3-byte all-ones RLE block, skipped by length
    chunk = np.frombuffer(
        native_corpus.v2_page(3, value=9)
        + native_corpus.v2_page(3, value=9, def_len=len(levels), levels=levels),
        dtype=np.uint8)
    plan = fused.ColumnPlan('x')
    plan.itemsize = 8
    plan.phys_dtype = np.dtype(np.int64)
    plan.out_dtype = np.dtype(np.int64)
    plan.out_shape = (6,)
    plan.chunk_len = chunk.size
    plan.out_bound = 6 * 8
    out = np.empty(48, np.uint8)
    lib = native._load_library()
    (res,) = fused.read_into(lib, [chunk], [plan], 6, out, [0])
    assert res[0] == 0, res
    np.testing.assert_array_equal(np.frombuffer(out, np.int64), np.full(6, 9))


def test_data_page_v2_corrupt_rejected():
    """v2 regressions: a page with real nulls must not fuse (the values
    region would be short), and over-declared level lengths must be rejected
    at scan time, never skipped past the chunk."""
    lib = native._load_library()

    def run(chunk_bytes, rows=4):
        chunk = np.frombuffer(chunk_bytes, dtype=np.uint8)
        plan = fused.ColumnPlan('x')
        plan.itemsize = 8
        plan.phys_dtype = np.dtype(np.int64)
        plan.out_dtype = np.dtype(np.int64)
        plan.out_shape = (rows,)
        plan.chunk_len = chunk.size
        plan.out_bound = rows * 8
        out = np.zeros(rows * 8, np.uint8)
        (res,) = fused.read_into(lib, [chunk], [plan], rows, out, [0])
        return res[0]

    assert run(native_corpus.v2_page(4, num_nulls=1)) == 5   # kColDefLevels
    assert run(native_corpus.v2_overdeclared_levels_chunk()) == 5
    assert run(native_corpus.v2_page(4, rep_len=1 << 30)) == 5
    # a truncated v2 header must fail parse, not over-read
    good = native_corpus.v2_page(4)
    assert run(good[:len(good) // 2]) in (1, 5, 8)


def test_flba_snappy_parity(tmp_path):
    """RawTensorCodec FLBA chunks ride the fused path when snappy-compressed
    (uncompressed PLAIN chunks keep the zero-copy view path)."""
    schema = Unischema('R', [
        UnischemaField('t', np.float32, (3, 4), RawTensorCodec(), False),
        UnischemaField('i', np.int64, (), ScalarCodec(np.int64), False),
    ])
    url = 'file://' + str(tmp_path / 'store')
    rng = np.random.default_rng(1)
    rows = [{'t': rng.random((3, 4)).astype(np.float32), 'i': i} for i in range(20)]
    # explicit per-column dict: the writer would otherwise honor the codec's
    # 'none' preference and keep the FLBA chunk on the zero-copy view path
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=5,
                            compression={'t': 'snappy', 'i': 'snappy'})
    path = _parquet_path(tmp_path / 'store')
    assert pq.read_metadata(path).row_group(0).column(0).compression == 'SNAPPY'
    pf = native.NativeParquetFile(path)
    block, rest = pf.read_fused(0, ['t', 'i'], schema.fields)
    assert 't' in block
    for r, got in zip(rows[:5], block['t']):
        np.testing.assert_array_equal(got, r['t'])
    assert block['t'].flags.writeable


def test_nullable_proven_null_free_fused(tmp_path):
    """OPTIONAL chunks whose statistics PROVE null_count == 0 fuse (the RLE
    def-levels block is skipped natively); chunks with a real null fall back
    with reason 'nullable'."""
    schema = Unischema('N', [
        UnischemaField('x', np.float32, (4,), RawTensorCodec(), True),
        UnischemaField('id', np.int64, (), ScalarCodec(np.int64), False),
    ])
    url = 'file://' + str(tmp_path / 'store')
    rows = [{'x': np.arange(4, dtype=np.float32) + i, 'id': i} for i in range(6)]
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=3,
                            compression={'x': 'snappy', 'id': 'snappy'})
    path = _parquet_path(tmp_path / 'store')
    pf = native.NativeParquetFile(path)
    block, rest = pf.read_fused(0, ['x', 'id'], schema.fields)
    assert 'x' in block
    np.testing.assert_array_equal(block['x'][2], rows[2]['x'])

    url2 = 'file://' + str(tmp_path / 'nulls')
    rows2 = [{'x': None if i == 1 else np.arange(4, dtype=np.float32), 'id': i}
             for i in range(6)]
    write_petastorm_dataset(url2, schema, iter(rows2), rows_per_row_group=3,
                            compression={'x': 'snappy', 'id': 'snappy'})
    pf2 = native.NativeParquetFile(_parquet_path(tmp_path / 'nulls'))
    plan = pf2.fused_plan(0, ['x'], schema.fields)
    assert plan.reasons.get('x') == 'nullable'


def test_ndarray_npy_cells_parity(tmp_path):
    url = 'file://' + str(tmp_path / 'store')
    schema = Unischema('A', [
        UnischemaField('a', np.uint8, (None, 6), NdarrayCodec(), False),
        UnischemaField('id', np.int64, (), ScalarCodec(np.int64), False),
    ])
    rng = np.random.default_rng(2)
    rows = [{'a': rng.integers(0, 255, (5, 6), np.uint8), 'id': i} for i in range(12)]
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=4)
    pf = native.NativeParquetFile(_parquet_path(tmp_path / 'store'))
    block, rest = pf.read_fused(0, ['a', 'id'], schema.fields)
    assert 'a' in block and block['a'].shape == (4, 5, 6)
    for r, got in zip(rows[:4], block['a']):
        np.testing.assert_array_equal(got, r['a'])
    assert block['a'].flags.writeable  # NdarrayCodec's writable-decode contract


def test_ragged_npy_cells_fall_back_correctly(tmp_path):
    """Cells with differing shapes inside one row group are non-uniform: the
    fused pass must refuse (status 'nonuniform') and the reader must still
    produce correct rows through the Arrow path."""
    url = 'file://' + str(tmp_path / 'store')
    schema = Unischema('A', [
        UnischemaField('a', np.uint8, (None, 2), NdarrayCodec(), False),
        UnischemaField('id', np.int64, (), ScalarCodec(np.int64), False),
    ])
    rows = [{'a': np.full((1 + i % 3, 2), i, np.uint8), 'id': i} for i in range(6)]
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=6)
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False) as r:
        got = {int(row.id): row.a for row in r}
    for row in rows:
        np.testing.assert_array_equal(got[row['id']], row['a'])


def test_image_column_fused_parity(tmp_path):
    pytest.importorskip('cv2')
    from petastorm_tpu.native import image_codec
    if not image_codec.is_available():
        pytest.skip('native image codec unavailable')
    schema = Unischema('I', [
        UnischemaField('img', np.uint8, (8, 10, 3), CompressedImageCodec('png'), False),
        UnischemaField('id', np.int32, (), ScalarCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'store')
    rng = np.random.default_rng(3)
    rows = [{'img': rng.integers(0, 255, (8, 10, 3), np.uint8), 'id': i}
            for i in range(10)]
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=5)
    pf = native.NativeParquetFile(_parquet_path(tmp_path / 'store'))
    block, rest = pf.read_fused(0, ['img', 'id'], schema.fields)
    assert 'img' in block and block['img'].shape == (5, 8, 10, 3)
    for r, got in zip(rows[:5], block['img']):
        np.testing.assert_array_equal(got, r['img'])  # png is lossless


def test_batch_reader_numeric_fused_respects_logical_types(tmp_path):
    """Plain-store fusing is codec-agnostic: numerics fuse with their logical
    dtype recovered (narrow/unsigned INT annotations), annotated flavors
    (timestamps) stay on the Arrow path."""
    path = tmp_path / 'plain'
    path.mkdir()
    table = pa.table({
        'i64': pa.array(np.arange(40, dtype=np.int64)),
        'u8': pa.array(np.arange(40, dtype=np.uint8)),
        'ts': pa.array(np.arange(40, dtype=np.int64), pa.timestamp('us')),
    })
    pq.write_table(table, str(path / 'f.parquet'), compression='snappy',
                   use_dictionary=['i64', 'u8', 'ts'])
    with make_batch_reader('file://' + str(path), reader_pool_type='dummy',
                           shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert batch.i64.dtype == np.int64 and batch.i64.tolist() == list(range(40))
    assert batch.u8.dtype == np.uint8 and batch.u8.tolist() == list(range(40))
    assert np.issubdtype(batch.ts.dtype, np.datetime64)


# ---------------------------------------------------------------------------
# one GIL transition per batch
# ---------------------------------------------------------------------------

def test_one_gil_transition_per_fused_batch(tmp_path, monkeypatch):
    url, schema, rows = _write_scalar_store(tmp_path, 'snappy', repeated=True)
    pf = native.NativeParquetFile(_parquet_path(tmp_path / 'store'))
    cols = list(schema.fields)
    calls = []
    real = fused._invoke_read_fused

    def counting(*a):
        calls.append(a)
        return real(*a)

    monkeypatch.setattr(fused, '_invoke_read_fused', counting)
    scans = []
    monkeypatch.setattr(pagescan, '_scan_chunk',
                        lambda *a, **k: (scans.append(1), None)[1])
    block, rest = pf.read_fused(0, cols, schema.fields)
    assert rest == [] and set(block) == set(cols)
    assert len(calls) == 1  # ONE native transition for the whole batch
    assert not scans        # and no per-column page-scan calls on the side


# ---------------------------------------------------------------------------
# fallback attribution
# ---------------------------------------------------------------------------

def test_unsupported_compression_reason_counted(tmp_path):
    path = tmp_path / 'gz'
    path.mkdir()
    table = pa.table({'x': pa.array(np.arange(10, dtype=np.int64))})
    pq.write_table(table, str(path / 'f.parquet'), compression='gzip',
                   use_dictionary=['x'])
    obs.get_registry().reset()
    pf = native.NativeParquetFile(str(path / 'f.parquet'))
    block, rest = pf.read_fused(0, ['x'], None)
    assert block == {} and rest == ['x']
    counters = _counters()
    assert counters.get('fused_fallback_reason:compression', 0) >= 1
    assert counters.get('fused_fallback_column:x:compression', 0) >= 1


def test_fused_fallback_table_rendering():
    from petastorm_tpu.observability.diagnose import (format_fused_fallbacks,
                                                      fused_fallback_table)
    diag = {'fused_fallback_column:a:compression': 3,
            'fused_fallback_column:b:nullable': 1,
            'unrelated': 7}
    table = fused_fallback_table(diag)
    assert table == {'a': {'compression': 3}, 'b': {'nullable': 1}}
    text = format_fused_fallbacks(diag)
    assert 'compression x3' in text and 'b' in text
    assert format_fused_fallbacks({'other': 1}) == ''


def test_decode_collate_share_helper():
    share = obs.decode_collate_share({'stage_pool_wait_s': 10.0,
                                      'stage_decode_s': 0.5,
                                      'stage_collate_s': 0.3,
                                      'stage_fused_decode_s': 2.0})
    assert share == {'decode_collate_share': 0.08, 'fused_decode_share': 0.2}
    assert obs.decode_collate_share({}) is None


# ---------------------------------------------------------------------------
# _MAX_PAGES: loud fallback
# ---------------------------------------------------------------------------

# the handwritten thrift page builders live in test_util/native_corpus.py so
# the sanitized fuzz-replay lane (test_sanitized_native.py) drives the SAME
# corpus through ASan/UBSan-instrumented kernels
from petastorm_tpu.test_util import native_corpus  # noqa: E402

_tvarint = native_corpus.tvarint
_plain_page = native_corpus.plain_page
_dict_page = native_corpus.dict_page


def test_page_cap_overflow_is_loud(monkeypatch):
    import types
    chunk = np.frombuffer(_plain_page(2) * 3, dtype=np.uint8)
    meta = types.SimpleNamespace(data_page_offset=0,
                                 total_compressed_size=chunk.size,
                                 path_in_schema='x')
    lib = native._load_library()
    obs.get_registry().reset()
    monkeypatch.setattr(pagescan, '_MAX_PAGES', 2)
    monkeypatch.setattr(pagescan, '_page_cap_warned', False)
    assert pagescan._scan_chunk(lib, chunk, meta) is None
    assert _counters().get('pagescan_fallback_reason:page-cap', 0) == 1
    # a 2-page chunk under the same cap still scans
    ok = np.frombuffer(_plain_page(2) * 2, dtype=np.uint8)
    meta.total_compressed_size = ok.size
    assert pagescan._scan_chunk(lib, ok, meta) is not None


def test_handwritten_pages_decode_through_fused():
    """The thrift builder used by the fuzzers must itself be valid input."""
    chunk = np.frombuffer(_plain_page(3, value=7) * 2, dtype=np.uint8)
    plan = fused.ColumnPlan('x')
    plan.itemsize = 8
    plan.phys_dtype = np.dtype(np.int64)
    plan.out_dtype = np.dtype(np.int64)
    plan.out_shape = (6,)
    plan.chunk_len = chunk.size
    plan.out_bound = 6 * 8
    out = np.empty(48, np.uint8)
    lib = native._load_library()
    (res,) = fused.read_into(lib, [chunk], [plan], 6, out, [0])
    assert res[0] == 0
    np.testing.assert_array_equal(np.frombuffer(out, np.int64), np.full(6, 7))


def test_dict_declared_count_overflow_rejected():
    """A corrupt dictionary page declaring 2**61 entries used to wrap the
    ``num_values * itemsize`` bounds product to 0, so any 32-bit index passed
    the ``k < n_dict`` guard and the per-row copy read far outside the real
    8-byte dictionary (regression: the check is division-based now)."""
    dict_vals = struct.pack('<q', 42)                    # ONE real entry
    idx = bytes([8]) + _tvarint(4 << 1) + bytes([200])   # RLE run: 4 × index 200
    chunk = np.frombuffer(_dict_page(1 << 61, dict_vals)
                          + _plain_page(4, values=idx, encoding=2),
                          dtype=np.uint8)
    plan = fused.ColumnPlan('x')
    plan.itemsize = 8
    plan.phys_dtype = np.dtype(np.int64)
    plan.out_dtype = np.dtype(np.int64)
    plan.out_shape = (4,)
    plan.chunk_len = chunk.size
    plan.out_bound = 4 * 8
    out = np.zeros(32, np.uint8)
    lib = native._load_library()
    (res,) = fused.read_into(lib, [chunk], [plan], 4, out, [0])
    assert res[0] == 9  # kColDict: rejected, never dereferenced


def test_precheck_failed_column_keeps_aux_alignment():
    """A column failing the read_into precheck (stale metadata) must not shift
    later columns' aux buffers: the npy header of a strip-npy column was read
    at the wrong index (silent wrong dtype) or raised IndexError, which
    upstream turned into discarding the whole fused batch."""
    import io
    cells = []
    for i in range(2):
        buf = io.BytesIO()
        np.save(buf, np.arange(3, dtype=np.int64) + i)
        cells.append(buf.getvalue())
    values = b''.join(struct.pack('<I', len(c)) + c for c in cells)
    chunk = np.frombuffer(_plain_page(2, values=values), dtype=np.uint8)
    payload = 3 * 8
    bad = fused.ColumnPlan('bad')
    bad.chunk_len = chunk.size + 1   # precheck: stale metadata, never decoded
    bad.out_bound = 16
    good = fused.ColumnPlan('good')
    good.mode = fused.MODE_BINARY_RAW
    good.strip_npy = True
    good.chunk_len = chunk.size
    good.out_bound = 2 * payload
    out = np.zeros(16 + 2 * payload, np.uint8)
    lib = native._load_library()
    res = fused.read_into(lib, [chunk, chunk], [bad, good], 2, out, [0, 16])
    assert res[0][0] != 0
    status, out_used, _aux0, aux1, header = res[1]
    assert status == 0 and out_used == 2 * payload
    assert aux1 > 0 and header == cells[0][:aux1]  # col 1's OWN npy header
    np.testing.assert_array_equal(
        np.frombuffer(out[16:16 + 2 * payload].tobytes(), np.int64),
        np.concatenate([np.arange(3), np.arange(3) + 1]))


# ---------------------------------------------------------------------------
# native predicate pushdown: parity, page-stat skipping, single GIL call
# ---------------------------------------------------------------------------

def _pred_cases():
    """Every natively-pushable clause shape, each with a Python ``do_include``
    oracle the fused verdicts must match row-for-row. Store values are
    ``i * 7 + 1`` for i in [0, 64) = 1..442 in row groups of 16."""
    return [
        ('range', in_range('c_int64', lo=100, hi=300)),
        ('range-exclusive', in_range('c_int64', lo=106, hi=302,
                                     lo_inclusive=False, hi_inclusive=False)),
        ('in', in_set([1, 106, 441, 9999], 'c_int64')),
        ('not-in', in_negate(in_set([1, 106, 442], 'c_int64'))),
        ('and', in_reduce([in_range('c_int64', lo=50),
                           in_range('c_float64', hi=200.0)], all)),
        ('float-range', in_range('c_float64', lo=33.5)),
    ]


@pytest.mark.parametrize('compression', ['snappy', 'zstd', 'lz4', 'none'])
def test_fused_predicate_parity(tmp_path, compression):
    """The filtered fused read returns exactly the rows the predicate's own
    ``do_include`` keeps — every clause shape, every codec — with zero
    ``predicate`` fallbacks."""
    url, schema, rows = _write_scalar_store(tmp_path, compression,
                                            repeated=False)
    path = _parquet_path(tmp_path / 'store')
    pf = native.NativeParquetFile(path)
    md = pq.read_metadata(path)
    cols = list(schema.fields)
    obs.get_registry().reset()
    obs.configure('counters')
    for label, pred in _pred_cases():
        clauses = pred.native_clauses()
        assert clauses is not None, label
        fields = sorted(pred.get_fields())
        expect = [r for r in rows
                  if pred.do_include({f: r[f] for f in pred.get_fields()})]
        got = []
        for rg in range(md.num_row_groups):
            res = pf.read_fused_predicate(rg, cols, fields, clauses,
                                          schema.fields)
            assert res is not None, (label, compression, rg)
            block, rest, sel_mask, n_selected, _skipped = res
            assert rest == [], (label, rest)
            assert int(sel_mask.sum()) == n_selected
            for k in range(n_selected):
                got.append({name: block[name][k] for name in cols})
        assert len(got) == len(expect), label
        for g, e in zip(got, expect):
            for name in cols:
                assert g[name] == e[name], (label, name)
    counters = _counters()
    assert not any(':predicate' in k for k in counters), counters
    assert counters.get('fused_pred_batches_total', 0) > 0


@pytest.mark.parametrize('compression', ['snappy', 'zstd'])
def test_fused_predicate_page_stat_skip(tmp_path, compression):
    """A row group whose single data page is excluded wholesale by its
    min/max page statistics decodes NOTHING: zero selected rows and a
    nonzero page-skip count (strictly less decode work than an unfiltered
    read — the acceptance contract)."""
    url, schema, rows = _write_scalar_store(tmp_path, compression,
                                            repeated=False)
    pf = native.NativeParquetFile(_parquet_path(tmp_path / 'store'))
    cols = list(schema.fields)
    # row group 3 holds values 337..442; hi=100 excludes every page by stats
    pred = in_range('c_int64', hi=100)
    res = pf.read_fused_predicate(3, cols, ['c_int64'],
                                  pred.native_clauses(), schema.fields)
    assert res is not None
    block, rest, sel_mask, n_selected, skipped = res
    assert n_selected == 0 and not sel_mask.any()
    assert skipped > 0
    for name in block:
        assert len(block[name]) == 0


def test_reader_native_predicate_end_to_end(tmp_path):
    """make_reader with a composed pushable predicate on a zstd store: the
    row set matches the Python oracle, batches ride the fused predicate
    stage, pages get stat-skipped, and no predicate column falls back."""
    url, schema, rows = _write_scalar_store(tmp_path, 'zstd', repeated=False)
    obs.get_registry().reset()
    obs.configure('counters')
    pred = in_reduce([in_range('c_int64', lo=100, hi=300),
                      in_negate(in_set([106], 'c_int64'))], all)
    with make_reader(url, predicate=pred, reader_pool_type='dummy',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        got = sorted(int(r.c_int64) for r in reader)
    expect = sorted(int(r['c_int64']) for r in rows
                    if 100 <= r['c_int64'] <= 300 and r['c_int64'] != 106)
    assert got == expect
    counters = _counters()
    assert counters.get('fused_pred_batches_total', 0) > 0
    assert counters.get('fused_pred_pages_skipped_total', 0) > 0
    assert not any(':predicate' in k for k in counters), counters


def test_one_gil_transition_per_filtered_batch(tmp_path, monkeypatch):
    """Structural twin of the unfiltered one-GIL test: predicate evaluation,
    page skipping and selected-row collation are ONE native call — and the
    unfiltered entry point is never touched on the side."""
    url, schema, rows = _write_scalar_store(tmp_path, 'snappy', repeated=False)
    pf = native.NativeParquetFile(_parquet_path(tmp_path / 'store'))
    cols = list(schema.fields)
    pred_calls, unfiltered_calls = [], []
    real = fused._invoke_read_fused_pred
    monkeypatch.setattr(fused, '_invoke_read_fused_pred',
                        lambda *a: (pred_calls.append(a), real(*a))[1])
    monkeypatch.setattr(fused, '_invoke_read_fused',
                        lambda *a: unfiltered_calls.append(a))
    pred = in_range('c_int64', lo=100, hi=300)
    res = pf.read_fused_predicate(0, cols, ['c_int64'],
                                  pred.native_clauses(), schema.fields)
    assert res is not None
    block, rest, _sel_mask, n_selected, _skipped = res
    assert rest == [] and n_selected > 0
    assert len(pred_calls) == 1   # ONE native transition, filter included
    assert not unfiltered_calls


@pytest.mark.parametrize('compression', ['snappy', 'zstd', 'lz4', 'none'])
def test_write_compression_knob_roundtrip(tmp_path, compression):
    """The materialize-side ``compression=`` knob round-trips through every
    supported codec: the written chunks carry the requested codec and the
    reader serves bit-exact rows with zero compression fallbacks."""
    url, schema, rows = _write_scalar_store(tmp_path, compression,
                                            repeated=True)
    md = pq.read_metadata(_parquet_path(tmp_path / 'store'))
    written = md.row_group(0).column(0).compression
    if compression == 'none':
        assert written == 'UNCOMPRESSED'
    else:
        assert written.lower().startswith(compression[:3])
    obs.get_registry().reset()
    obs.configure('counters')
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                     num_epochs=1) as reader:
        got = sorted(int(r.c_int64) for r in reader)
    assert got == sorted(int(r['c_int64']) for r in rows)
    assert _counters().get('fused_fallback_reason:compression', 0) == 0


# ---------------------------------------------------------------------------
# robustness / fuzz: malformed bytes must return the sentinel, never crash
# ---------------------------------------------------------------------------

def _fuzz_one(lib, data):
    native_corpus.replay_chunk_through_kernels(lib, data, fused.REASON_BY_STATUS)


def test_fuzz_page_parsers_seeded():
    lib = native._load_library()
    for data in native_corpus.fuzz_corpus():
        _fuzz_one(lib, data)


def test_fuzz_snappy_and_hybrid_hypothesis():
    hypothesis = pytest.importorskip('hypothesis')
    from hypothesis import strategies as st
    lib = native._load_library()

    @hypothesis.settings(max_examples=120, deadline=None)
    @hypothesis.given(st.binary(max_size=160))
    def run(data):
        _fuzz_one(lib, data)

    run()


def test_fuzz_compressed_frames_corpus():
    """The handwritten zstd/lz4 frame corpus against the release kernel:
    positive controls decode byte-exactly, malformed frames are rejected
    (the same corpus replays under ASan/UBSan in test_sanitized_native)."""
    native_corpus.replay_compressed_frames(native._load_library())
    native_corpus.replay_page_stats(native._load_library())


def test_fuzz_compressed_frames_hypothesis():
    """Single-byte flips over every handwritten zstd/lz4 frame, replayed
    through every decompressor dispatch AND the predicate kernel: the
    sentinel contract must hold at any mutation site."""
    hypothesis = pytest.importorskip('hypothesis')
    from hypothesis import strategies as st
    lib = native._load_library()
    frames = [bytes(case) for case, _codec, _ok, _vals
              in native_corpus.compressed_frame_corpus()]

    @hypothesis.settings(max_examples=150, deadline=None)
    @hypothesis.given(st.data())
    def run(data):
        raw = data.draw(st.sampled_from(frames))
        pos = data.draw(st.integers(0, len(raw) - 1))
        val = data.draw(st.integers(0, 255))
        mutated = bytearray(raw)
        mutated[pos] = val
        _fuzz_one(lib, bytes(mutated))

    run()


def test_fuzz_page_stats_hypothesis():
    """Random bytes spliced in as the v1 Statistics struct: the page-header
    stats parser must parse or reject without ever reading past the chunk."""
    hypothesis = pytest.importorskip('hypothesis')
    from hypothesis import strategies as st
    lib = native._load_library()

    @hypothesis.settings(max_examples=120, deadline=None)
    @hypothesis.given(st.binary(max_size=48))
    def run(stats):
        _fuzz_one(lib, _plain_page(4, stats=stats + b'\x00'))

    run()


# ---------------------------------------------------------------------------
# shm-ring reserve/commit (the in-place channel)
# ---------------------------------------------------------------------------

def _ring(name, capacity=4096):
    from petastorm_tpu.native import shm_ring
    if not shm_ring.is_available():
        pytest.skip('shm ring unavailable')
    return shm_ring.ShmRing.create('/pstpu_test_{}_{}'.format(name, os.getpid()),
                                   capacity)


def test_ring_reserve_commit_roundtrip_with_wraps():
    r = _ring('rsv')
    try:
        for i in range(60):
            payload = bytes([i % 251]) * (i * 37 % 900 + 10)
            mv = r.try_reserve(len(payload))
            assert mv is not None
            mv[:len(payload)] = payload
            r.commit(len(payload))
            assert r.try_read() == payload
    finally:
        r.close()


def test_ring_reserve_interleaves_with_writev():
    r = _ring('mix')
    try:
        for i in range(60):
            if i % 2:
                assert r.try_write(b'x' * ((i * 53) % 1000 + 5))
                assert r.try_read() is not None
            else:
                n = (i * 91) % 1000 + 5
                mv = r.try_reserve(n)
                mv[:n] = bytes([7]) * n
                r.commit(n)
                assert r.try_read() == bytes([7]) * n
    finally:
        r.close()


def test_ring_reserve_abort_and_short_commit():
    r = _ring('abort')
    try:
        r.try_reserve(100)
        r.abort()
        assert r.try_read() is None and not r.has_message()
        mv = r.try_reserve(500)
        mv[:10] = b'ABCDEFGHIJ'
        r.commit(10)  # commit fewer bytes than reserved
        assert r.try_read() == b'ABCDEFGHIJ'
        with pytest.raises(ValueError):
            r.try_reserve(5000)  # can never fit
    finally:
        r.close()


def test_ring_reserve_wrap_never_fits_raises():
    """max_len alone fits the ring, but at a tail position where wrapping is
    required, pad + header + payload exceeds capacity — even a fully drained
    ring can never satisfy it. reserve must fail loudly (callers fall back to
    the copy channel) instead of returning retry and polling forever."""
    r = _ring('nofit')  # capacity 4096
    try:
        # advance the tail to 2000 and drain: the region before the physical
        # end is too small for the payload, and the wrap pad (~2096 bytes)
        # plus header plus payload overflows capacity
        assert r.try_write(b'x' * 1992)
        assert r.try_read() is not None
        with pytest.raises(ValueError):
            r.try_reserve(3000)
        # no pad marker leaked; smaller reservations still work at this tail
        mv = r.try_reserve(100)
        mv[:3] = b'abc'
        r.commit(3)
        assert r.try_read() == b'abc'
    finally:
        r.close()


def test_serializer_frame_for_layout_matches_serialize():
    from petastorm_tpu.serializers import NumpyBlockSerializer
    s = NumpyBlockSerializer()
    block = {'a': np.arange(12, dtype=np.int64).reshape(3, 4),
             'b': np.arange(3, dtype=np.float32)}
    meta = [('a', block['a'].dtype.str, block['a'].shape, None),
            ('b', block['b'].dtype.str, block['b'].shape, None)]
    prefix = s.frame_for_layout(meta)
    wire = prefix + memoryview(block['a']).cast('B') + memoryview(block['b']).cast('B')
    assert bytes(wire) == bytes(s.serialize(block))
    out = s.deserialize(bytearray(wire))
    np.testing.assert_array_equal(out['a'], block['a'])
    np.testing.assert_array_equal(out['b'], block['b'])


def test_process_pool_inplace_fused_publish(tmp_path):
    """End-to-end: a fixed-layout store through the process pool assembles
    its batches IN the ring slots (fused_inplace_batches_total > 0) and the
    consumer sees bit-exact writable blocks."""
    from petastorm_tpu.native import shm_ring
    if not shm_ring.is_available():
        pytest.skip('shm ring unavailable')
    schema = Unischema('R', [
        UnischemaField('image', np.uint8, (16, 16, 3), RawTensorCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(np.int64), False),
    ])
    url = 'file://' + str(tmp_path / 'raw')
    rng = np.random.default_rng(0)
    data = [{'image': rng.integers(0, 255, (16, 16, 3), np.uint8), 'label': i}
            for i in range(40)]
    write_petastorm_dataset(url, schema, iter(data), rows_per_row_group=8,
                            compression='none')
    obs.configure('counters')
    with make_reader(url, reader_pool_type='process', workers_count=1,
                     output='columnar', shuffle_row_groups=False,
                     num_epochs=1, telemetry='counters') as reader:
        blocks = list(reader)
        diag = reader.diagnostics
    assert diag.get('fused_inplace_batches_total', 0) >= 1
    labels = [int(v) for b in blocks for v in np.asarray(b.label)]
    assert labels == list(range(40))
    for b in blocks:
        img = np.asarray(b.image)
        assert img.flags.writeable
        for row_img, lab in zip(img, np.asarray(b.label)):
            np.testing.assert_array_equal(row_img, data[int(lab)]['image'])


# ---------------------------------------------------------------------------
# end-to-end: the bench-shaped store rides fully fused with zero fallbacks
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('compression', ['snappy', 'zstd'])
def test_hello_world_shaped_store_fully_fused(tmp_path, compression):
    pytest.importorskip('cv2')
    from petastorm_tpu.native import image_codec
    if not image_codec.is_available():
        pytest.skip('native image codec unavailable')
    schema = Unischema('H', [
        UnischemaField('id', np.int32, (), ScalarCodec(), False),
        UnischemaField('image1', np.uint8, (16, 24, 3), CompressedImageCodec('png'), False),
        UnischemaField('array_4d', np.uint8, (None, 4, 5, None), NdarrayCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'hw')
    rng = np.random.default_rng(42)
    rows = [{'id': i,
             'image1': rng.integers(0, 255, (16, 24, 3), np.uint8),
             'array_4d': rng.integers(0, 255, (2, 4, 5, 3), np.uint8)}
            for i in range(30)]
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=10,
                            compression=compression)
    obs.get_registry().reset()
    obs.configure('counters')
    with make_reader(url, reader_pool_type='thread', workers_count=2,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        got = {int(r.id): r for r in reader}
    assert len(got) == 30
    for r in rows:
        np.testing.assert_array_equal(got[r['id']].image1, r['image1'])
        np.testing.assert_array_equal(got[r['id']].array_4d, r['array_4d'])
    counters = _counters()
    # the acceptance contract: previously Arrow-only encodings (the
    # dictionary-encoded id column, the snappy npy cells) ride the native
    # path with their fallback counters at ZERO
    assert counters.get('fused_batches_total', 0) >= 3
    assert counters.get('fused_columns_total', 0) >= 9
    assert not any(k.startswith('fused_fallback') for k in counters), counters
