"""Shared reader service (docs/serve.md): broker, fan-out ring, fair share,
eviction, daemon lifecycle, and the multi-consumer protocol verification.

The in-process tests drive :class:`ReaderService` directly (no subprocess);
the daemon-lifecycle tests spawn the real ``python -m petastorm_tpu.serve``
process through ``make_reader(serve=<dir>)`` exactly as users do.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.errors import (ConsumerEvictedError, EmptyResultError,
                                  ProtocolViolation, ServeDaemonDiedError,
                                  ServeError)
from petastorm_tpu.workers.ventilator import FairShareVentilator


def _base_spec(url, **overrides):
    spec = dict(dataset_url=url, batch_reader=False, schema_fields=None,
                seed=0, shuffle_row_groups=False, shuffle_row_drop_partitions=1,
                predicate=None, rowgroup_selector=None, num_epochs=1,
                cur_shard=None, shard_count=None, transform_spec=None,
                ngram=None, columnar_ngram=False, storage_retry_policy=None,
                chunk_cache=None, chunk_cache_size_limit=None, cache=None)
    spec.update(overrides)
    return spec


def _make_service(tmp_path, **kwargs):
    from petastorm_tpu.serve.service import ReaderService
    defaults = dict(pool_type='thread', workers_count=2, idle_timeout_s=None)
    defaults.update(kwargs)
    svc = ReaderService(str(tmp_path / 'svc'), **defaults)
    svc.start()
    return svc


def _consume_rows(reply, out, key, limit=None, schema_key='transformed_schema'):
    """Drain one attached consumer's stream into ``out[key]``."""
    from petastorm_tpu.native.shm_ring import BcastRing
    from petastorm_tpu.row_worker import RowResultsQueueReader
    from petastorm_tpu.serve.client import _ServedPoolFacade
    ring = BcastRing.attach(reply['ring_name'])
    facade = _ServedPoolFacade(ring, reply['token'], reply['daemon_pid'],
                               reply['tenant_id'])
    rqr = RowResultsQueueReader(reply['client_plan'][schema_key])
    rows = []
    try:
        while limit is None or len(rows) < limit:
            rows.append(rqr.read_next(facade))
    except EmptyResultError:
        pass
    finally:
        out[key] = rows
        ring.close()
    return facade


# ---------------------------------------------------------------------------
# FairShareVentilator units
# ---------------------------------------------------------------------------

def test_fairshare_weighted_round_robin_and_budgets():
    dispatched = []
    done = []
    fsv = FairShareVentilator(lambda **kw: dispatched.append(kw),
                              on_tenant_done=done.append)
    fsv.start()
    try:
        fsv.add_tenant('a', [{'i': n} for n in range(6)], iterations=1,
                       weight=2, max_in_flight=100)
        fsv.add_tenant('b', [{'i': n} for n in range(6)], iterations=1,
                       weight=1, max_in_flight=100)
        deadline = time.monotonic() + 5
        while len(dispatched) < 12 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(dispatched) == 12
        # weighted interleave: in any prefix while both have backlog, tenant a
        # (weight 2) stays ahead of or equal to 2x tenant b's count per cycle;
        # the hard guarantee asserted: b is never starved for a full cycle
        order = [fsv.tenant_of_seq(kw['_seq']) for kw in dispatched]
        # all seqs resolved while in flight
        assert set(order) <= {'a', 'b', None}
        first_nine = [t for t in order[:9] if t is not None]
        assert 'b' in first_nine[:4], order  # starvation-free
        # completions release budgets and fire per-tenant done exactly once
        for kw in dispatched:
            fsv.processed_item(kw['_seq'])
        deadline = time.monotonic() + 5
        while len(done) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sorted(done) == ['a', 'b']
    finally:
        fsv.stop()


def test_fairshare_in_flight_budget_gates_dispatch():
    dispatched = []
    fsv = FairShareVentilator(lambda **kw: dispatched.append(kw))
    fsv.start()
    try:
        fsv.add_tenant('a', [{'i': n} for n in range(10)], iterations=1,
                       weight=1, max_in_flight=2)
        time.sleep(0.3)
        assert len(dispatched) == 2  # admission control: budget caps in-flight
        fsv.processed_item(dispatched[0]['_seq'])
        deadline = time.monotonic() + 5
        while len(dispatched) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(dispatched) == 3
        stats = fsv.tenant_stats()['a']
        assert stats['in_flight'] == 2 and stats['dispatched'] == 3
    finally:
        fsv.stop()


def test_fairshare_remove_tenant_mid_epoch_drains_silently():
    dispatched = []
    done = []
    fsv = FairShareVentilator(lambda **kw: dispatched.append(kw),
                              on_tenant_done=done.append)
    fsv.start()
    try:
        fsv.add_tenant('a', [{'i': n} for n in range(50)], iterations=1,
                       weight=1, max_in_flight=2)
        deadline = time.monotonic() + 5
        while len(dispatched) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fsv.remove_tenant('a')
        n_at_removal = len(dispatched)
        for kw in list(dispatched):
            fsv.processed_item(kw['_seq'])
        time.sleep(0.2)
        assert len(dispatched) == n_at_removal  # nothing new fed
        assert done == []                       # removed tenants never "finish"
        final = fsv.tenant_stats()['a']         # live bookkeeping reclaimed,
        assert final['removed'] and final['in_flight'] == 0  # counters retained
    finally:
        fsv.stop()


def test_fairshare_skewed_demand_respects_weights():
    """Under saturated demand the DISPATCH ORDER tracks weights — a weight-2
    tenant gets two slots per scheduling cycle to a weight-1 tenant's one —
    while the light tenant is never starved for a full cycle."""
    dispatched = []
    order = []
    lock = threading.Lock()

    def record(**kw):
        with lock:
            dispatched.append(kw['_seq'])
            order.append(fsv.tenant_of_seq(kw['_seq']))

    fsv = FairShareVentilator(record)
    fsv.start()
    try:
        fsv.add_tenant('heavy', [{'i': n} for n in range(40)], iterations=1,
                       weight=2, max_in_flight=100)
        fsv.add_tenant('light', [{'i': n} for n in range(40)], iterations=1,
                       weight=1, max_in_flight=100)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with lock:
                if len(order) >= 60:
                    break
            time.sleep(0.005)
        with lock:
            prefix = order[:30]
        # while both tenants have backlog, every 3-dispatch cycle is 2 heavy +
        # 1 light; allow cycle-boundary jitter from the race between add_tenant
        # and the first refill
        heavy = prefix.count('heavy')
        assert 17 <= heavy <= 23, prefix
        # starvation-freedom: light appears in every window of one full cycle
        for i in range(0, 27, 3):
            assert 'light' in prefix[i:i + 4], prefix
        for seq in dispatched:
            fsv.processed_item(seq)
    finally:
        fsv.stop()


# ---------------------------------------------------------------------------
# broadcast ring units
# ---------------------------------------------------------------------------

def _bcast_or_skip():
    from petastorm_tpu.native import shm_ring
    if not shm_ring.is_available():
        pytest.skip('shm ring library unavailable')
    return shm_ring


def test_bcast_min_head_reclamation_and_tokens():
    shm_ring = _bcast_or_skip()
    name = '/pstpu_t_bc_{}'.format(os.getpid())
    ring = shm_ring.BcastRing.create(name, 4096)
    try:
        consumer = shm_ring.BcastRing.attach(name)
        t1, t2 = ring.join(), ring.join()
        payload = b'x' * 900
        wrote = 0
        while ring.try_write(payload):
            wrote += 1
        assert wrote >= 3
        # the slot is released per consumer by its own cursor advance: space
        # frees only after the LAST attached consumer passes it
        assert not ring.try_write(payload)
        assert consumer.try_read_view(t1) is not None
        assert not ring.try_write(payload)     # t2 still pins the bytes
        assert consumer.try_read_view(t2) is not None
        assert ring.try_write(payload)         # reclaimed exactly then
        # graceful leave frees the slot for a re-grant; the stale token dies
        consumer.leave(t2)
        t3 = ring.join()
        with pytest.raises(shm_ring.BcastConsumerGone) as e:
            consumer.try_read_view(t2)
        assert not e.value.evicted
        assert ring.consumer_count() == 2
        assert t3 != t2
        consumer.close()
    finally:
        ring.close()


def test_bcast_eviction_unblocks_producer_and_is_loud():
    shm_ring = _bcast_or_skip()
    name = '/pstpu_t_bc_ev_{}'.format(os.getpid())
    ring = shm_ring.BcastRing.create(name, 4096)
    try:
        consumer = shm_ring.BcastRing.attach(name)
        fast, slow = ring.join(), ring.join()
        payload = b'y' * 1500
        assert ring.try_write(payload)
        assert consumer.try_read_view(fast) is not None
        assert ring.try_write(payload)
        assert consumer.try_read_view(fast) is not None
        assert not ring.try_write(payload)  # slow consumer pins 2 messages
        assert ring.lag(slow) > ring.lag(fast)
        ring.evict(slow)
        assert ring.try_write(payload)      # fleet unblocked
        with pytest.raises(shm_ring.BcastConsumerGone) as e:
            consumer.try_read_view(slow)
        assert e.value.evicted
        consumer.close()
    finally:
        ring.close()


def test_idle_wait_escalates_and_counts_spins():
    from petastorm_tpu.native.shm_ring import IdleWait
    obs.configure('counters')
    obs.get_registry().reset()
    idle = IdleWait(spins=8, yields=4, sleep_s=0.0001, max_sleep_s=0.0004)
    t0 = time.monotonic()
    for _ in range(8):
        idle.wait()          # spin tier: no sleep
    spin_elapsed = time.monotonic() - t0
    assert spin_elapsed < 0.05
    for _ in range(10):
        idle.wait()          # yield then sleep tier
    idle.reset()
    counters = obs.snapshot()['counters']
    assert counters.get('ring_idle_spins', 0) >= 8


# ---------------------------------------------------------------------------
# service lifecycle matrix (in-process daemon)
# ---------------------------------------------------------------------------

def test_two_consumers_share_one_decode(tmp_path, synthetic_dataset):
    svc = _make_service(tmp_path)
    try:
        spec = _base_spec(synthetic_dataset.url)
        r1 = svc.attach(dict(spec))
        r2 = svc.attach(dict(spec))
        assert r1['stream_id'] == r2['stream_id']
        out = {}
        threads = [threading.Thread(target=_consume_rows, args=(r, out, k))
                   for r, k in ((r1, 'a'), (r2, 'b'))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
        assert all(not t.is_alive() for t in threads), 'consumers hung'
        n = len(synthetic_dataset.data)
        assert len(out['a']) == len(out['b']) == n
        assert sorted(r.id for r in out['a']) == sorted(r.id for r in out['b'])
        stats = svc.stats()
        stream = stats['streams'][r1['stream_id']]
        # ONE decode served both: every batch decoded once, and the second
        # consumer's batches are all shared-decode hits
        assert stream['decoded_batches'] == 10
        assert sum(t['shared_decode_hits']
                   for t in stream['tenants'].values()) == 10
        assert stats['pool']['items_completed'] == 10
    finally:
        svc.shutdown()


def test_attach_mid_epoch_gets_suffix_and_detach_leaves_others(
        tmp_path, synthetic_dataset):
    svc = _make_service(tmp_path)
    try:
        spec = _base_spec(synthetic_dataset.url, num_epochs=3)
        r1 = svc.attach(dict(spec))
        out = {}
        t1 = threading.Thread(target=_consume_rows, args=(r1, out, 'a'))
        t1.start()
        # wait until the stream is demonstrably mid-flight, then join late
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            stream = svc.stats()['streams'].get(r1['stream_id'], {})
            if stream.get('decoded_batches', 0) >= 2:
                break
            time.sleep(0.01)
        r2 = svc.attach(dict(spec))
        assert r2['stream_id'] == r1['stream_id']
        out2 = {}
        t2 = threading.Thread(target=_consume_rows, args=(r2, out2, 'b'))
        t2.start()
        t1.join(120)
        t2.join(120)
        assert not t1.is_alive() and not t2.is_alive()
        n = len(synthetic_dataset.data)
        assert len(out['a']) == 3 * n          # the original tenant lost nothing
        assert 0 < len(out2['b']) <= 3 * n     # the late joiner got the suffix
        assert len(out2['b']) % n == 0 or len(out2['b']) < 3 * n
    finally:
        svc.shutdown()


def test_detach_mid_epoch_never_stalls_remaining(tmp_path, synthetic_dataset):
    svc = _make_service(tmp_path)
    try:
        spec = _base_spec(synthetic_dataset.url, num_epochs=2)
        r1 = svc.attach(dict(spec))
        r2 = svc.attach(dict(spec))
        out = {}
        t1 = threading.Thread(target=_consume_rows, args=(r1, out, 'a'))
        t1.start()
        # tenant 2 reads a few rows then detaches mid-epoch
        _consume_rows(r2, out, 'b', limit=5)
        assert svc.detach(r2['tenant_id'])
        t1.join(120)
        assert not t1.is_alive()
        assert len(out['a']) == 2 * len(synthetic_dataset.data)
        assert len(out['b']) == 5
    finally:
        svc.shutdown()


def test_slow_consumer_is_evicted_not_stalling(tmp_path, scalar_dataset):
    svc = _make_service(tmp_path, ring_bytes=65536, evict_block_s=0.3)
    try:
        spec = _base_spec(scalar_dataset.url, batch_reader=True,
                          num_epochs=30, columnar_ngram=False)
        r_fast = svc.attach(dict(spec))
        r_slow = svc.attach(dict(spec))
        from petastorm_tpu.batch_worker import BatchResultsQueueReader
        from petastorm_tpu.native.shm_ring import BcastRing
        from petastorm_tpu.serve.client import _ServedPoolFacade
        ring = BcastRing.attach(r_fast['ring_name'])
        facade = _ServedPoolFacade(ring, r_fast['token'], r_fast['daemon_pid'],
                                   r_fast['tenant_id'])
        rqr = BatchResultsQueueReader(r_fast['client_plan']['transformed_schema'])
        batches = 0
        with pytest.raises(EmptyResultError):
            while True:
                rqr.read_next(facade)
                batches += 1
        assert batches == 300  # the fast consumer got EVERY batch
        # the slow consumer was evicted loudly, with a structured error
        slow_ring = BcastRing.attach(r_slow['ring_name'])
        slow_facade = _ServedPoolFacade(slow_ring, r_slow['token'],
                                        r_slow['daemon_pid'], r_slow['tenant_id'])
        with pytest.raises(ConsumerEvictedError):
            while True:
                slow_facade.get_results()
        stats = svc.stats()
        assert stats['evictions'] == 1
        tenant = stats['streams'][r_slow['stream_id']]['tenants'][
            r_slow['tenant_id']]
        assert tenant['evicted'] is True
        ring.close()
        slow_ring.close()
    finally:
        svc.shutdown()


def test_multi_stream_fair_share_occupancy_in_stats(tmp_path, synthetic_dataset,
                                                    scalar_dataset):
    """Two DIFFERENT streams share the fleet; stats expose per-stream
    fair-share occupancy summing to ~1."""
    svc = _make_service(tmp_path)
    try:
        r1 = svc.attach(_base_spec(synthetic_dataset.url), weight=1)
        r2 = svc.attach(_base_spec(scalar_dataset.url, batch_reader=True),
                        weight=1)
        assert r1['stream_id'] != r2['stream_id']
        out = {}
        threads = [
            threading.Thread(target=_consume_rows, args=(r1, out, 'a')),
        ]
        from petastorm_tpu.batch_worker import BatchResultsQueueReader
        from petastorm_tpu.native.shm_ring import BcastRing
        from petastorm_tpu.serve.client import _ServedPoolFacade

        def consume_batches():
            ring = BcastRing.attach(r2['ring_name'])
            facade = _ServedPoolFacade(ring, r2['token'], r2['daemon_pid'],
                                       r2['tenant_id'])
            rqr = BatchResultsQueueReader(r2['client_plan']['transformed_schema'])
            got = []
            try:
                while True:
                    got.append(rqr.read_next(facade))
            except EmptyResultError:
                pass
            out['b'] = got
            ring.close()

        threads.append(threading.Thread(target=consume_batches))
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert all(not t.is_alive() for t in threads)
        assert len(out['a']) == len(synthetic_dataset.data)
        assert sum(len(b[0]) for b in out['b']) == 100
        stats = svc.stats()
        occ = [s['fair_share'].get('occupancy', 0)
               for s in stats['streams'].values()]
        assert 0.99 < sum(occ) <= 1.01
    finally:
        svc.shutdown()


def test_seeded_chaos_with_serve_monitor_armed(tmp_path, scalar_dataset,
                                               monkeypatch):
    """A worker error mid-stream (seeded fault injection) quarantines the item
    (daemon policy on_error='skip'), the stream still terminates for every
    consumer, and the armed serve monitor accepts the whole event sequence."""
    from petastorm_tpu import faults
    monkeypatch.setenv('PSTPU_SERVE_MONITOR', '1')
    # error_times exceeds the daemon's retry budget so the item QUARANTINES
    # (a transient fault would just retry-and-succeed, serving all rows)
    faults.install(faults.FaultPlan(
        error_items=(0,), error_times=5,
        state_dir=tempfile.mkdtemp(prefix='serve_chaos_')))
    try:
        svc = _make_service(tmp_path)
        assert svc.monitor is not None
        try:
            spec = _base_spec(scalar_dataset.url, batch_reader=True)
            r1 = svc.attach(dict(spec))
            from petastorm_tpu.batch_worker import BatchResultsQueueReader
            from petastorm_tpu.native.shm_ring import BcastRing
            from petastorm_tpu.serve.client import _ServedPoolFacade
            ring = BcastRing.attach(r1['ring_name'])
            facade = _ServedPoolFacade(ring, r1['token'], r1['daemon_pid'],
                                       r1['tenant_id'])
            rqr = BatchResultsQueueReader(r1['client_plan']['transformed_schema'])
            rows = 0
            with pytest.raises(EmptyResultError):
                while True:
                    batch = rqr.read_next(facade)
                    rows += len(batch[0])
            # one row group quarantined; the epoch still TERMINATED
            assert rows == 90
            assert svc.stats()['pool']['items_quarantined'] == 1
            ring.close()
        finally:
            svc.shutdown()
    finally:
        faults.uninstall()


def test_blob_plane_parity_and_gc(tmp_path):
    """Batches over the blob threshold ride /dev/shm blobs: the fused decode
    lands them there directly (FusedBlobRef / SERVE_COLS) or the worker
    writes them once (BlobRef / SERVE_BLOB); consumers view the mapping with
    zero upfront copy, values are bit-exact, and the daemon's GC reclaims
    every file once the fleet consumed past it."""
    import glob
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('B', [
        UnischemaField('i', np.int64, (), ScalarCodec(np.int64), False),
        UnischemaField('t', np.uint8, (64, 64, 3), NdarrayCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'store')
    rng = np.random.default_rng(7)
    rows = [{'i': i, 't': rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)}
            for i in range(20)]
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=10)

    obs.get_registry().reset()
    svc = _make_service(tmp_path, blob_threshold_bytes=1,
                        blob_gc_grace_s=0.05)
    try:
        assert svc._blob_dir is not None
        spec = _base_spec(url)
        r1 = svc.attach(dict(spec))
        r2 = svc.attach(dict(spec))
        out = {}
        threads = [threading.Thread(target=_consume_rows, args=(r, out, key))
                   for r, key in ((r1, 'a'), (r2, 'b'))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(not t.is_alive() for t in threads)
        assert len(out['a']) == len(out['b']) == 20
        by_id = {row.i: row for row in out['b']}
        for want in rows:
            np.testing.assert_array_equal(by_id[want['i']].t, want['t'])
        counters = obs.snapshot()['counters']
        # the fused decode landed batches DIRECTLY in shared blobs
        assert counters.get('serve_fused_blob_batches_total', 0) >= 2, counters
        # blob GC: once the fleet consumed and the grace elapsed, the plane
        # is empty — nothing leaks into /dev/shm
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not glob.glob(os.path.join(svc._blob_dir, '*')):
                break
            time.sleep(0.05)
        assert not glob.glob(os.path.join(svc._blob_dir, '*'))
    finally:
        svc.shutdown()
    assert not os.path.isdir(svc._blob_dir or '')  # dir swept on shutdown


# ---------------------------------------------------------------------------
# real daemon lifecycle (subprocess via make_reader(serve=...))
# ---------------------------------------------------------------------------

def test_serve_single_tenant_parity_with_plain_reader(tmp_path, synthetic_dataset):
    svc_dir = str(tmp_path / 'svc')
    with make_reader(synthetic_dataset.url, serve=svc_dir, seed=0,
                     shuffle_row_groups=False, workers_count=2) as served:
        served_rows = {r.id: r for r in served}
    with make_reader(synthetic_dataset.url, seed=0, shuffle_row_groups=False,
                     workers_count=2) as plain:
        plain_rows = {r.id: r for r in plain}
    assert served_rows.keys() == plain_rows.keys()
    for i in sorted(plain_rows)[:10]:
        np.testing.assert_array_equal(served_rows[i].matrix, plain_rows[i].matrix)
    # same daemon serves a follow-up batch-reader attach too
    with make_batch_reader('file://' + synthetic_dataset.path, serve=svc_dir,
                           shuffle_row_groups=False) as served_b:
        total = sum(len(b[0]) for b in served_b)
    assert total == len(synthetic_dataset.data)
    from petastorm_tpu.serve.client import connect_service
    conn = connect_service(svc_dir)
    conn.send({'op': 'shutdown'})
    conn.recv()
    conn.close()


def test_serve_daemon_crash_raises_structured_error(tmp_path, synthetic_dataset):
    import signal
    svc_dir = str(tmp_path / 'svc')
    reader = make_reader(synthetic_dataset.url, serve=svc_dir, seed=0,
                         shuffle_row_groups=False, num_epochs=None)
    try:
        for _, _row in zip(range(5), reader):
            pass
        from petastorm_tpu.serve.service import read_endpoint
        pid = read_endpoint(svc_dir)['pid']
        os.kill(pid, signal.SIGKILL)
        with pytest.raises((ServeDaemonDiedError, ServeError)):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                next(reader)
    finally:
        reader.stop()
        reader.join()


def test_serve_rejects_unsupported_combinations(tmp_path, synthetic_dataset):
    with pytest.raises(ValueError, match='resume_state'):
        make_reader(synthetic_dataset.url, serve=str(tmp_path / 's1'),
                    resume_state={'version': 1})
    with pytest.raises(ValueError, match='autotune'):
        make_reader(synthetic_dataset.url, serve=str(tmp_path / 's2'),
                    autotune=True)


def test_stream_spec_canonicalization():
    from petastorm_tpu.serve.service import canonical_stream_id
    a = _base_spec('file:///data/x')
    b = _base_spec('file:///data/x')
    c = _base_spec('file:///data/x', num_epochs=2)
    assert canonical_stream_id(a) == canonical_stream_id(b)
    assert canonical_stream_id(a) != canonical_stream_id(c)


# ---------------------------------------------------------------------------
# the multi-consumer protocol: model checking + monitor
# ---------------------------------------------------------------------------

def test_serve_modelcheck_default_scope_exhausts_clean():
    """THE tier-1 gate: the extended multi-consumer scope exhausts within
    budget with zero invariant violations, above the declared state floor."""
    from petastorm_tpu.analysis.protocol import serve_spec as S
    cfg = S.ServeSpecConfig(**S.DEFAULT_SERVE_SCOPE)
    result = S.check(cfg, budget_s=300.0)
    assert result.exhausted, 'serve scope not exhausted in budget'
    assert result.violation is None, result.trace
    assert result.states >= S.DEFAULT_SERVE_STATE_FLOOR, result.states


@pytest.mark.parametrize('mutation', ['reclaim_ignores_slowest',
                                      'evict_keeps_delivering',
                                      'join_stale_cursor'])
def test_serve_mutations_have_teeth(mutation):
    from petastorm_tpu.analysis.protocol import serve_spec as S
    cfg = S.ServeSpecConfig(mutation=mutation, **S.DEFAULT_SERVE_SCOPE)
    result = S.check(cfg, budget_s=120.0)
    assert result.violation is not None, \
        'mutation {} produced no counterexample'.format(mutation)
    assert result.trace


def test_serve_modelcheck_cli():
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis.protocol.modelcheck',
         '--serve', '--mutate', 'reclaim_ignores_slowest'],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert 'no_overwritten_read' in proc.stdout


def test_serve_monitor_accepts_legal_and_rejects_illegal():
    from petastorm_tpu.analysis.protocol.monitor import ServeMonitor
    m = ServeMonitor()
    m.on_attach('t0', 's0')
    m.on_publish('s0', 0)
    m.on_publish('s0', 1)
    m.on_evict('t0')
    m.on_detach('t0')
    m.on_end('s0')
    with pytest.raises(ProtocolViolation):
        m.on_publish('s0', 2)       # publish after END
    m2 = ServeMonitor()
    m2.on_attach('t0', 's0')
    with pytest.raises(ProtocolViolation):
        m2.on_attach('t0', 's0')    # double attach
    m3 = ServeMonitor()
    m3.on_publish('s0', 5)
    with pytest.raises(ProtocolViolation):
        m3.on_publish('s0', 5)      # repeated seq = double publish
    m4 = ServeMonitor()
    m4.on_deliver(3)
    with pytest.raises(ProtocolViolation):
        m4.on_deliver(3)            # double delivery to one consumer
    m5 = ServeMonitor()
    m5.on_consumer_end()
    with pytest.raises(ProtocolViolation):
        m5.on_deliver(9)            # delivery after END
    with pytest.raises(ProtocolViolation):
        ServeMonitor().on_detach('ghost')


def test_serve_monitor_env_resolution(monkeypatch):
    from petastorm_tpu.analysis.protocol.monitor import (ServeMonitor,
                                                         serve_monitor_from_env)
    monkeypatch.delenv('PSTPU_SERVE_MONITOR', raising=False)
    monkeypatch.delenv('PSTPU_PROTOCOL_MONITOR', raising=False)
    assert serve_monitor_from_env(None, 'x') is None
    monkeypatch.setenv('PSTPU_SERVE_MONITOR', '1')
    assert isinstance(serve_monitor_from_env(None, 'x'), ServeMonitor)
    explicit = ServeMonitor()
    assert serve_monitor_from_env(explicit, 'x') is explicit
