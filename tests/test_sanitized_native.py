"""The sanitizer-instrumented fuzz lane (``PSTPU_SANITIZE``, docs/native.md).

The release fuzz tests (test_fused_decode.py) assert the error-sentinel
contract; an out-of-bounds READ the release build happens to survive still
passes them. This lane rebuilds the kernels with
``PSTPU_SANITIZE=address,undefined`` and replays the identical corpus
(``petastorm_tpu/test_util/native_corpus.py``) plus the handwritten
corrupt-chunk regressions and the shm-ring reserve/commit cycles through the
instrumented ``.san.so`` — any over-read/overflow/UB aborts the subprocess.

Slow-marked (a full ASan rebuild of the Arrow-linked kernel takes tens of
seconds) and skipped wherever the toolchain lacks the gcc sanitizer
runtimes. The replay runs in a subprocess because an instrumented shared
library only loads with ``libasan``/``libubsan`` preloaded.
"""

import os
import subprocess
import sys

import pytest

import petastorm_tpu
from petastorm_tpu.native import build as native_build

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(petastorm_tpu.__file__)))
SANITIZE = 'address,undefined'


def _runtime_lib(name):
    """Absolute path of a gcc sanitizer runtime, or None when the toolchain
    does not ship it (g++ echoes the bare name back for unknown files)."""
    try:
        out = subprocess.run(['g++', '-print-file-name={}'.format(name)],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = out.stdout.strip()
    return path if os.path.isabs(path) and os.path.exists(path) else None


@pytest.fixture(scope='module')
def sanitizer_env():
    asan = _runtime_lib('libasan.so')
    ubsan = _runtime_lib('libubsan.so')
    if asan is None or ubsan is None:
        pytest.skip('gcc sanitizer runtimes not installed')
    env = dict(os.environ)
    env.update({
        'PSTPU_SANITIZE': SANITIZE,
        'LD_PRELOAD': '{} {}'.format(asan, ubsan),
        # leak detection sees the interpreter's arena noise, not ours; any
        # real finding must abort loudly so the subprocess exits non-zero
        'ASAN_OPTIONS': 'detect_leaks=0:abort_on_error=1',
        'UBSAN_OPTIONS': 'halt_on_error=1:print_stacktrace=1',
        'PYTHONPATH': REPO_ROOT,
        'JAX_PLATFORMS': 'cpu',
    })
    return env


_DRIVER = '''\
"""Sanitized replay driver (written to a real file: spawn cannot run stdin)."""
import os
import sys

assert os.environ.get('PSTPU_SANITIZE') == {sanitize!r}

from petastorm_tpu.native import build
out = build.build(quiet=True)
assert out.endswith('.san.so'), out
shm_out = build.build_shm(quiet=True)
assert shm_out.endswith('.san.so'), shm_out

import petastorm_tpu.native as native
lib = native._load_library()
assert lib is not None, 'sanitized kernel failed to load'

from petastorm_tpu.native import fused, shm_ring
from petastorm_tpu.test_util import native_corpus

for data in native_corpus.fuzz_corpus():
    native_corpus.replay_chunk_through_kernels(lib, data, fused.REASON_BY_STATUS)
native_corpus.replay_corrupt_chunk_regressions(lib)

assert shm_ring.is_available(), 'sanitized shm ring failed to load'
native_corpus.replay_ring_cycles(shm_ring, str(os.getpid()))
native_corpus.replay_lifetime_cycles(shm_ring, str(os.getpid()))

print('SANITIZED-REPLAY-OK')
'''

_USE_AFTER_RELEASE_DRIVER = '''\
"""Deliberate use-after-release under the sanitized build + PROT_NONE guard:
a borrowed ring view is force-reclaimed out from under the consumer, and the
next touch MUST die (SIGSEGV via the guard page) instead of reading recycled
bytes. The parent test asserts this driver does NOT exit cleanly."""
import os
import sys

assert os.environ.get('PSTPU_LIFETIME_GUARD') == '1'

from petastorm_tpu.native import build
build.build_shm(quiet=True)
import numpy as np
from petastorm_tpu.native import shm_ring
from petastorm_tpu.native.lifetime import RingBorrowLedger, SlotRegistry

ring = shm_ring.ShmRing.create('/pstpu_uar_{}'.format(os.getpid()), 64 * 1024)
# an 8 KiB payload guarantees at least one fully-covered page to protect
assert ring.try_write(b'v' * 8192)
view, span, borrowed = ring.try_read_zero_copy()
assert borrowed, 'expected an in-place borrowed view'
ledger = RingBorrowLedger(ring, registry_=SlotRegistry())
slot = ledger.take(view, span, borrowed)
arr = np.frombuffer(view, dtype=np.uint8)  # the consumer's delivered array
slot.adopt(arr)
slot.seal()
slot.force_reclaim()  # reclaimer escalates over the live borrow -> PROT_NONE
print('PRE-TOUCH', flush=True)
print(int(arr.sum()))  # sweeps the guarded page: must die HERE
print('POST-TOUCH', flush=True)
'''


def test_sanitized_build_coexists_with_release(sanitizer_env, tmp_path):
    """PSTPU_SANITIZE builds land in their own flag-keyed ``.san.so`` + stamp
    and leave the release artifacts untouched."""
    release_so = native_build.SHM_OUTPUT
    release_stamp = None
    if os.path.exists(release_so + '.stamp'):
        with open(release_so + '.stamp') as f:
            release_stamp = f.read()
    driver = tmp_path / 'build_probe.py'
    driver.write_text(
        'from petastorm_tpu.native import build\n'
        'print(build.build_shm(quiet=True))\n')
    proc = subprocess.run([sys.executable, str(driver)], env=sanitizer_env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    san_so = proc.stdout.strip().splitlines()[-1]
    assert san_so.endswith('libpstpu_shm.san.so')
    assert os.path.exists(san_so)
    with open(san_so + '.stamp') as f:
        assert f.read().startswith('san[{}]:'.format(SANITIZE))
    # release artifacts untouched: both flavors coexist
    if release_stamp is not None:
        with open(release_so + '.stamp') as f:
            assert f.read() == release_stamp


def test_sanitize_env_validation(monkeypatch):
    monkeypatch.setenv('PSTPU_SANITIZE', 'address,bogus')
    with pytest.raises(RuntimeError, match='bogus'):
        native_build.sanitize_tokens()
    monkeypatch.setenv('PSTPU_SANITIZE', '')
    assert native_build.sanitize_tokens() == ()


def test_sanitized_fuzz_replay(sanitizer_env, tmp_path):
    """THE lane: the fused-decode fuzz corpus, the corrupt-chunk regressions
    and the ring reserve/commit cycles run clean under ASan+UBSan."""
    driver = tmp_path / 'sanitized_replay.py'
    driver.write_text(_DRIVER.format(sanitize=SANITIZE))
    proc = subprocess.run([sys.executable, str(driver)], env=sanitizer_env,
                          capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, \
        'sanitized replay failed\nstdout:\n{}\nstderr:\n{}'.format(
            proc.stdout, proc.stderr)
    assert 'SANITIZED-REPLAY-OK' in proc.stdout
    for marker in ('AddressSanitizer', 'runtime error'):
        assert marker not in proc.stderr, proc.stderr


def test_sanitized_use_after_release_is_caught(sanitizer_env, tmp_path):
    """The runtime twin of the PT1100 fixture's seeded defect: touching a
    force-reclaimed borrow dies loudly (guard page) under the sanitized
    build — it must NEVER complete and read recycled ring bytes."""
    driver = tmp_path / 'use_after_release.py'
    driver.write_text(_USE_AFTER_RELEASE_DRIVER)
    env = dict(sanitizer_env, PSTPU_LIFETIME_GUARD='1')
    proc = subprocess.run([sys.executable, str(driver)], env=env,
                          capture_output=True, text=True, timeout=560)
    assert 'PRE-TOUCH' in proc.stdout, proc.stdout + proc.stderr
    assert 'POST-TOUCH' not in proc.stdout, \
        'use-after-release read recycled bytes undetected:\n' + proc.stdout
    assert proc.returncode != 0
