"""Pipeline telemetry tests: metrics registry, cross-process aggregation,
trace ring bounding + Chrome trace schema, stall attribution, exporters, the
unified pool diagnostics schema, and the telemetry-off overhead guard."""

import json
import time

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.jax.loader import JaxDataLoader
from petastorm_tpu.observability.metrics import MetricsRegistry, merge_snapshots
from petastorm_tpu.observability.trace import TraceRing


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Telemetry state is process-global: save/restore the level and clear
    registry + ring around every test so tests neither pollute nor depend on
    each other."""
    saved = obs.current_config()
    obs.get_registry().reset()
    obs.get_ring().clear()
    yield
    obs.configure(saved)
    obs.get_registry().reset()
    obs.get_ring().clear()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    reg.counter('rows').inc(3)
    reg.counter('rows').inc()
    reg.counter('wait_s').add(0.25)
    reg.gauge('depth').set(7)
    reg.histogram('lat', buckets=(0.1, 1.0)).observe(0.05)
    reg.histogram('lat', buckets=(0.1, 1.0)).observe(0.5)
    reg.histogram('lat', buckets=(0.1, 1.0)).observe(5.0)
    snap = reg.snapshot()
    assert snap['counters']['rows'] == 4
    assert snap['counters']['wait_s'] == pytest.approx(0.25)
    assert snap['gauges']['depth'] == 7
    assert snap['histograms']['lat']['count'] == 3
    assert snap['histograms']['lat']['counts'] == [1, 1, 1]
    flat = obs.flatten_snapshot(snap)
    assert flat['rows'] == 4 and flat['lat_count'] == 3


def test_metric_kind_conflict_raises():
    reg = MetricsRegistry()
    reg.counter('x')
    with pytest.raises(TypeError):
        reg.gauge('x')


def test_merge_snapshots_sums_across_processes():
    a = {'counters': {'rows': 3}, 'gauges': {'occ': 2},
         'histograms': {'lat': {'bounds': [1.0], 'counts': [1, 0], 'sum': 0.5, 'count': 1}}}
    b = {'counters': {'rows': 5, 'other': 1}, 'gauges': {'occ': 4},
         'histograms': {'lat': {'bounds': [1.0], 'counts': [0, 2], 'sum': 4.0, 'count': 2}}}
    merged = merge_snapshots([a, b])
    assert merged['counters'] == {'rows': 8, 'other': 1}
    assert merged['gauges'] == {'occ': 6}
    assert merged['histograms']['lat']['counts'] == [1, 2]
    assert merged['histograms']['lat']['count'] == 3


def test_telemetry_config_resolution():
    assert obs.resolve_telemetry(None) is None
    cfg = obs.resolve_telemetry('spans')
    assert cfg.level == 'spans'
    assert obs.resolve_telemetry(cfg) is cfg
    with pytest.raises(ValueError):
        obs.resolve_telemetry('loud')
    with pytest.raises(ValueError):
        obs.TelemetryConfig(level='bogus')


# ---------------------------------------------------------------------------
# trace ring
# ---------------------------------------------------------------------------

def test_trace_ring_bounded_rotation():
    ring = TraceRing(capacity=8)
    for i in range(3 * 8):
        ring.add({'name': 'e{}'.format(i), 'ph': 'X', 'ts': i, 'dur': 1,
                  'pid': 1, 'tid': 1})
    assert len(ring) == 8
    events = ring.snapshot()
    # oldest rotated out: only the last 8 remain, in order
    assert [e['name'] for e in events] == ['e{}'.format(i) for i in range(16, 24)]
    assert ring.dropped == 16


def test_trace_ring_drain_and_absorb():
    ring = TraceRing(capacity=4)
    ring.add({'name': 'a'})
    drained = ring.drain()
    assert [e['name'] for e in drained] == ['a']
    assert len(ring) == 0
    ring.extend(drained)
    assert len(ring) == 1


def test_span_noop_below_spans_level():
    obs.configure('counters')
    with obs.span('invisible'):
        pass
    assert len(obs.get_ring()) == 0
    obs.configure('spans')
    with obs.span('visible'):
        pass
    assert [e['name'] for e in obs.get_ring().snapshot()] == ['visible']


def test_chrome_trace_export_schema(tmp_path):
    obs.configure('spans')
    with obs.stage('decode', cat='worker', rows=10):
        time.sleep(0.001)
    obs.instant('chunk_hit', cat='chunkstore')
    out = tmp_path / 'trace.json'
    n = obs.export_chrome_trace(str(out))
    assert n == 2
    doc = json.loads(out.read_text())  # loads == the Perfetto-parseable bar
    events = doc['traceEvents']
    assert len(events) == 2
    for event in events:
        assert {'ph', 'ts', 'dur', 'pid', 'tid', 'name'} <= set(event)
        assert event['ph'] == 'X'
    decode = next(e for e in events if e['name'] == 'decode')
    assert decode['dur'] >= 1000  # µs
    assert decode['args']['rows'] == 10


# ---------------------------------------------------------------------------
# end-to-end: counters through the reader/loader, per pool type
# ---------------------------------------------------------------------------

def _drain_loader(reader, batch_size=20):
    with JaxDataLoader(reader, batch_size=batch_size, drop_last=False) as loader:
        total = 0
        for batch in loader:
            first = next(iter(batch.values()))
            total += len(first)
        return total, loader.diagnostics


def test_counters_flow_thread_pool(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=2,
                         output='columnar', telemetry='counters')
    total, diag = _drain_loader(reader)
    assert total == 100
    assert diag['worker_rows_decoded_total'] == 100
    # the id column rides the fused native pass (one stage for read+decode);
    # either attribution route must carry the worker's busy seconds
    assert (diag.get('stage_fused_decode_s', 0) > 0
            or (diag['stage_read_s'] > 0 and diag['stage_decode_s'] > 0))
    assert diag['stage_pool_wait_s'] > 0
    assert diag['stage_ventilate_count'] == diag['items_completed'] == 10
    assert diag['rows_emitted'] == 100


def test_cross_process_counter_aggregation(synthetic_dataset):
    """Worker-side stage counters recorded in SPAWNED processes must surface
    in the main process's diagnostics — they travel the results channel as
    cumulative snapshots, the same route the payloads ride."""
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='process', workers_count=2,
                         output='columnar', telemetry='counters')
    try:
        total, diag = _drain_loader(reader)
    finally:
        pass  # _drain_loader's context stopped the reader already
    assert total == 100
    # these counters are only ever incremented inside the worker processes
    assert diag['worker_rows_decoded_total'] == 100
    assert (diag.get('stage_fused_decode_s', 0) > 0
            or (diag['stage_read_s'] > 0 and diag['stage_decode_s'] > 0))
    # and they arrived as per-pid snapshots, not via this process's registry
    assert obs.get_registry().snapshot()['counters'].get(
        'worker_rows_decoded_total') is None


def test_loader_diagnostics_full_keyset_before_iteration(synthetic_dataset):
    """Regression: pre-fix, rows_emitted/reader_wait_* were simply absent
    until the first __iter__, forcing .get guards on every consumer."""
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', telemetry='counters')
    with JaxDataLoader(reader, batch_size=10) as loader:
        diag = loader.diagnostics
        assert diag['rows_emitted'] == 0
        assert diag['reader_wait_s'] == 0.0
        assert diag['reader_wait_fraction'] == 0.0


def test_unified_pool_diagnostics_schema():
    """Every pool type reports the same diagnostics keys and units."""
    from petastorm_tpu.workers import DummyPool, ProcessPool, ThreadPool
    expected = {'workers_count', 'items_ventilated', 'items_completed',
                'items_in_flight', 'results_queue_depth',
                'worker_restarts', 'items_requeued', 'items_quarantined',
                # process-global shared-plane borrow accounting
                # (docs/native.md): one family across every pool type
                'lifetime_live_borrows', 'lifetime_blocked_reclaims',
                'lifetime_guard_faults'}
    pools = [DummyPool(), ThreadPool(2), ProcessPool(2)]
    for pool in pools:
        # the process pool additionally reports its delivery mode
        extras = {'zero_copy'} if isinstance(pool, ProcessPool) else set()
        assert set(pool.diagnostics) == expected | extras, type(pool).__name__
        assert pool.telemetry_snapshots() == []
        assert all(isinstance(v, int) for v in pool.diagnostics.values())


# ---------------------------------------------------------------------------
# stall attribution
# ---------------------------------------------------------------------------

def test_stall_report_unit_decomposition():
    diag = {'reader_wait_s': 1.0, 'reader_wait_fraction': 0.5,
            'stage_pool_wait_s': 0.8, 'stage_read_s': 0.1,
            'stage_decode_s': 0.7, 'stage_transform_s': 0.0}
    report = obs.stall_report(diag)
    assert report['coverage'] == pytest.approx(1.0)
    # assembly = wait - pool_wait; worker split proportional to busy seconds
    assert report['stages']['consumer.assembly'] == pytest.approx(0.2)
    assert report['stages']['worker.decode'] == pytest.approx(0.8 * 0.7 / 0.8)
    assert report['bottleneck'] == 'worker.decode'
    text = obs.format_stall_report(report)
    assert 'worker.decode' in text and 'bottleneck' in text


def test_stall_report_chunk_fetch_not_double_counted():
    # chunk fetches happen INSIDE the read stage: the report must subtract
    # them from read IO, never count the same second twice
    diag = {'reader_wait_s': 1.0, 'stage_pool_wait_s': 1.0,
            'stage_read_s': 0.6, 'stage_chunk_fetch_s': 0.5,
            'stage_decode_s': 0.0}
    report = obs.stall_report(diag)
    assert report['worker_busy_s']['read_io'] == pytest.approx(0.1)
    assert report['worker_busy_s']['chunk_fetch'] == pytest.approx(0.5)
    assert report['bottleneck'] == 'worker.chunk_fetch'
    assert sum(report['stages'].values()) == pytest.approx(1.0, abs=1e-6)


def test_stall_report_unattributed_when_workers_untimed():
    report = obs.stall_report({'reader_wait_s': 1.0, 'stage_pool_wait_s': 0.9})
    assert report['stages']['pool.unattributed'] == pytest.approx(0.9)
    assert report['coverage'] == pytest.approx(1.0)


def _slow_batched_transform(batch):
    time.sleep(0.02)
    return batch


def test_stall_attribution_names_synthetic_slow_stage(synthetic_dataset):
    """A deliberately slow worker transform must dominate the measured worker
    busy time AND the report must attribute >=90% of the wait to named
    stages (the acceptance bar)."""
    from petastorm_tpu.transform import TransformSpec
    spec = TransformSpec(_slow_batched_transform, batched=True)
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar', transform_spec=spec,
                         telemetry='counters')
    total, diag = _drain_loader(reader)
    assert total == 100
    report = obs.stall_report(diag)
    assert report['coverage'] >= 0.9
    busy = report['worker_busy_s']
    assert busy['transform'] > max(busy['read_io'], busy['decode'], busy['chunk_fetch'])
    assert report['bottleneck'] == 'worker.transform'


# ---------------------------------------------------------------------------
# telemetry off: near-zero overhead, no per-row work
# ---------------------------------------------------------------------------

def test_telemetry_off_records_nothing(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar', telemetry='off')
    total, diag = _drain_loader(reader)
    assert total == 100
    snap = obs.get_registry().snapshot()
    assert snap['counters'] == {} and snap['gauges'] == {}
    assert len(obs.get_ring()) == 0
    # the loader's own wait accounting is independent of the telemetry level
    assert diag['rows_emitted'] == 100


def test_counters_level_no_per_row_calls(synthetic_dataset, monkeypatch):
    """The hot-loop contract: telemetry work happens at block/batch
    granularity. Count every observability entry point call during a full
    100-row read — the total must scale with blocks+batches (10+5 here), not
    rows."""
    calls = {'n': 0}

    def counting(fn):
        def wrapper(*a, **k):
            calls['n'] += 1
            return fn(*a, **k)
        return wrapper

    for name in ('stage', 'span', 'count', 'gauge_set', 'instant', 'observe',
                 'add_seconds'):
        monkeypatch.setattr(obs, name, counting(getattr(obs, name)))
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar', telemetry='counters')
    total, _ = _drain_loader(reader, batch_size=20)
    assert total == 100
    # 10 blocks + 5 batches, ~11 instrumentation points each => ~110 calls of
    # block-level budget. ONE per-row call site would add >= 100 on top, so
    # 150 cleanly separates block-granularity from per-row regressions.
    assert calls['n'] <= 150, calls['n']


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_exposition_format():
    reg = obs.get_registry()
    reg.counter('rows_total').inc(42)
    reg.gauge('occupancy').set(3)
    reg.histogram('wait', buckets=(0.1, 1.0)).observe(0.05)
    text = obs.to_prometheus_text()
    assert '# TYPE pstpu_rows_total counter' in text
    assert 'pstpu_rows_total 42' in text
    assert '# TYPE pstpu_occupancy gauge' in text
    assert 'pstpu_wait_bucket{le="0.1"} 1' in text
    assert 'pstpu_wait_bucket{le="+Inf"} 1' in text
    assert 'pstpu_wait_count 1' in text


def test_jsonl_exporter_flushes(tmp_path):
    obs.get_registry().counter('rows_total').inc(7)
    path = tmp_path / 'metrics.jsonl'
    with obs.JsonlExporter(str(path), interval_s=0.05):
        time.sleep(0.12)
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) >= 2  # at least one interval flush + the stop flush
    assert all('ts' in rec and rec['metrics']['rows_total'] == 7 for rec in lines)


def test_diagnose_cli_smoke(synthetic_dataset, tmp_path, capsys):
    from petastorm_tpu.observability.diagnose import main as diagnose_main
    trace = tmp_path / 'diag_trace.json'
    rc = diagnose_main([synthetic_dataset.url, '--batches', '3', '--batch-size', '10',
                        '-p', 'dummy', '-w', '1', '--trace-out', str(trace),
                        '--prom-out', str(tmp_path / 'm.prom')])
    assert rc == 0
    out = capsys.readouterr().out
    assert 'stall report' in out and 'diagnostics:' in out
    doc = json.loads(trace.read_text())
    assert doc['traceEvents'], 'spans level must record events'
    assert (tmp_path / 'm.prom').read_text().startswith('# TYPE')


def test_spans_level_records_pipeline_stages(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar', telemetry='spans')
    total, _ = _drain_loader(reader)
    assert total == 100
    names = {e['name'] for e in obs.get_ring().snapshot()}
    assert {'ventilate', 'pool_wait', 'collate'} <= names
    # the worker's read+decode seconds live in ONE fused span on fused
    # stores, or in the classic read/decode pair on the Arrow path
    assert 'fused_decode' in names or {'read', 'decode'} <= names
