"""Worker supervision and fault tolerance (docs/robustness.md).

The chaos matrix the supervision layer is accepted against:

* SIGKILL mid-item (process pool) -> the epoch completes with every
  non-quarantined row delivered EXACTLY once, ``worker_restarts >= 1``, and no
  ``TimeoutWaitingForResultError``.
* a deterministic poison row group under ``on_error='skip'`` -> one
  quarantine record, complete epoch — on process, thread AND dummy pools
  (the policy is pool-independent).
* ``on_error='raise'`` -> fast failure carrying the worker-side traceback.
* ``on_error='retry'`` -> transient item errors are retried and the epoch
  completes in full.
* storage faults injected through ``retry.py`` exercise the transient
  backoff path.
* overhead guards: supervision works at item granularity — heartbeats and
  ownership tracking add ZERO per-row work (the PR-3-style structural bound)
  and <1% warm throughput (timing guard, slow-marked).

All faults come from ``petastorm_tpu.faults`` — deterministic, seeded into
the REAL code paths, coordinated across spawned workers via one-shot state
files.

Every test in this module runs with the worker-pool protocol conformance
monitor attached (``docs/protocol.md``; the autouse fixture below): each
crash/requeue/poison scenario therefore proves not just end-state row counts
but that every observed event sequence walked the supervision protocol spec —
any stale-drop, requeue, or accounting divergence raises
:class:`~petastorm_tpu.errors.ProtocolViolation` on the spot.
"""

import collections
import time

import pytest

from petastorm_tpu import faults, make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.errors import (EmptyResultError, PetastormTpuError, PoisonItemError,
                                  TimeoutWaitingForResultError, WorkerTerminationRequested)
from petastorm_tpu.retry import RetryPolicy
from petastorm_tpu.workers import DummyPool, ErrorPolicy, ProcessPool, ThreadPool
from petastorm_tpu.workers.supervision import attach_remote_context

ALL_POOL_TYPES = ['thread', 'dummy']  # in-process matrix; 'process' has dedicated tests


@pytest.fixture(autouse=True)
def _protocol_monitor_on(monkeypatch):
    """Arm the protocol conformance monitor for every pool this module
    constructs — the whole chaos matrix doubles as a conformance proof."""
    monkeypatch.setenv('PSTPU_PROTOCOL_MONITOR', '1')


@pytest.fixture
def fault_state(tmp_path):
    """State dir for one-shot faults; always disarms the hooks afterwards."""
    yield str(tmp_path)
    faults.uninstall()


def _drain_ids(reader):
    ids = []
    for batch in reader:
        ids.extend(int(x) for x in batch.id)
    return ids


# ---------------------------------------------------------------------------
# error taxonomy (satellite: everything roots at PetastormTpuError)
# ---------------------------------------------------------------------------

def test_worker_errors_root_at_petastorm_tpu_error():
    for exc in (EmptyResultError, TimeoutWaitingForResultError,
                WorkerTerminationRequested, PoisonItemError):
        assert issubclass(exc, PetastormTpuError)
    # the historical import location keeps working
    from petastorm_tpu.workers.worker_base import EmptyResultError as alias
    assert alias is EmptyResultError


def test_error_policy_validation():
    with pytest.raises(ValueError, match='on_error'):
        ErrorPolicy('explode')
    with pytest.raises(ValueError, match='max_item_retries'):
        ErrorPolicy('skip', -1)
    with pytest.raises(ValueError, match='on_error'):
        make_reader('file:///nonexistent', on_error='explode')


def test_attach_remote_context_preserves_type_and_traceback():
    exc = ValueError('boom')
    out = attach_remote_context(exc, 'Traceback ...worker side...', worker_id=3, seq=7, pid=42)
    assert out is exc
    assert exc.worker_traceback == 'Traceback ...worker side...'
    assert exc.item_seq == 7
    assert 'worker 3 (pid 42)' in str(exc.__cause__)
    assert 'worker side' in str(exc.__cause__)


# ---------------------------------------------------------------------------
# fault plan mechanics
# ---------------------------------------------------------------------------

def test_fault_plan_one_shot_needs_state_dir():
    with pytest.raises(ValueError, match='state_dir'):
        faults.FaultPlan(kill_items=(1,), kill_once=True)
    with pytest.raises(ValueError, match='state_dir'):
        faults.FaultPlan(error_items=(1,), error_times=2)


def test_storage_faults_exercise_retry_backoff(fault_state):
    faults.install(faults.FaultPlan(storage_fail_first=2))
    calls = {'n': 0}

    def op():
        calls['n'] += 1
        return 'ok'

    policy = RetryPolicy(max_attempts=4, initial_backoff_s=0.001)
    assert policy.call(op) == 'ok'
    # two injected ECONNRESETs consumed two attempts before op succeeded
    assert calls['n'] == 1
    faults.uninstall()
    from petastorm_tpu import retry
    assert retry.FAULT_POINT is None  # hook disarmed


def test_kill_fault_degrades_to_error_outside_spawned_worker(fault_state):
    faults.install(faults.FaultPlan(kill_items=(5,), kill_once=False, state_dir=fault_state))
    with pytest.raises(faults.FaultInjectedError, match='degraded to an error'):
        faults.on_item({'piece_index': 5})


# ---------------------------------------------------------------------------
# THE chaos test: SIGKILL mid-item, exactly-once epoch (process pool)
# ---------------------------------------------------------------------------

def test_sigkill_mid_item_epoch_completes_exactly_once(synthetic_dataset, fault_state):
    faults.install(faults.FaultPlan(kill_items=(3,), kill_once=True, state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='process', workers_count=2,
                     output='columnar', seed=0) as reader:
        ids = _drain_ids(reader)  # no TimeoutWaitingForResultError may surface
        counts = collections.Counter(ids)
        assert len(ids) == 100, 'every row of every row group must be delivered'
        assert all(v == 1 for v in counts.values()), 'exactly once: no duplicates'
        diag = reader.diagnostics
        assert diag['worker_restarts'] >= 1
        assert diag['items_requeued'] >= 1
        assert diag['items_quarantined'] == 0
        assert diag['items_ventilated'] == diag['items_completed'] == 10
        assert diag['items_in_flight'] == 0


def test_process_pool_poison_quarantine_and_raise(synthetic_dataset, fault_state):
    """One poison row group on the process pool: 'skip' quarantines it with a
    worker-side traceback in the record; 'raise' surfaces the remote traceback."""
    faults.install(faults.FaultPlan(error_items=(2,), state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='process', workers_count=1,
                     output='columnar', seed=0,
                     on_error='skip', max_item_retries=1) as reader:
        ids = _drain_ids(reader)
        assert len(ids) == 90 and len(set(ids)) == 90
        records = reader.quarantined_items
        assert len(records) == 1
        assert records[0]['kind'] == 'error' and records[0]['attempts'] == 2
        assert 'FaultInjectedError' in records[0]['error']
        assert 'injected poison' in records[0]['traceback']
        assert reader.diagnostics['items_quarantined'] == 1

    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='process', workers_count=1,
                     output='columnar', seed=0, on_error='raise') as reader:
        with pytest.raises(faults.FaultInjectedError) as exc_info:
            _drain_ids(reader)
        assert 'injected poison' in exc_info.value.worker_traceback
        assert 'worker-side traceback' in str(exc_info.value.__cause__)


# ---------------------------------------------------------------------------
# the same policy matrix on the in-process pools
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('pool_type', ALL_POOL_TYPES)
def test_poison_skip_completes_epoch(synthetic_dataset, fault_state, pool_type):
    faults.install(faults.FaultPlan(error_items=(2,), state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type=pool_type, workers_count=2,
                     output='columnar', seed=0,
                     on_error='skip', max_item_retries=1) as reader:
        ids = _drain_ids(reader)
        assert len(ids) == 90 and len(set(ids)) == 90
        records = reader.quarantined_items
        assert len(records) == 1
        assert records[0]['kind'] == 'error'
        assert 'injected poison' in records[0]['traceback']
        diag = reader.diagnostics
        assert diag['items_quarantined'] == 1
        assert diag['items_requeued'] == 1  # one retry before quarantine
        assert diag['items_ventilated'] == diag['items_completed'] == 10


@pytest.mark.parametrize('pool_type', ALL_POOL_TYPES)
def test_poison_raise_surfaces_traceback(synthetic_dataset, fault_state, pool_type):
    faults.install(faults.FaultPlan(error_items=(2,), state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type=pool_type, workers_count=2,
                     output='columnar', seed=0, on_error='raise') as reader:
        with pytest.raises(faults.FaultInjectedError) as exc_info:
            _drain_ids(reader)
        assert 'injected poison' in exc_info.value.worker_traceback
        assert exc_info.value.item_seq is not None


@pytest.mark.parametrize('pool_type', ALL_POOL_TYPES)
def test_transient_error_retry_recovers_full_epoch(synthetic_dataset, fault_state, pool_type):
    faults.install(faults.FaultPlan(error_items=(4,), error_times=1, state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type=pool_type, workers_count=2,
                     output='columnar', seed=0,
                     on_error='retry', max_item_retries=2) as reader:
        ids = _drain_ids(reader)
        assert sorted(ids) == list(range(100))
        diag = reader.diagnostics
        assert diag['items_requeued'] == 1
        assert diag['items_quarantined'] == 0


def test_retry_budget_exhaustion_raises(synthetic_dataset, fault_state):
    faults.install(faults.FaultPlan(error_items=(4,), state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='thread', workers_count=1,
                     output='columnar', seed=0,
                     on_error='retry', max_item_retries=1) as reader:
        with pytest.raises(faults.FaultInjectedError):
            _drain_ids(reader)


# ---------------------------------------------------------------------------
# the requeue_published divergence (found by the protocol model checker):
# an item that publishes and THEN errors must not be re-run — the payload
# already reached the consumer, so a requeue delivers the rows twice. These
# tests replay the minimized counterexample (dispatch -> claim -> publish ->
# error -> requeue -> re-publish) against the REAL pools via
# PublishThenErrorWorker; before the fix every pool double-delivered.
# ---------------------------------------------------------------------------

def _drain_pool(pool, timeout_s=None):
    got = []
    while True:
        try:
            got.append(pool.get_results(**({'timeout_s': timeout_s}
                                           if timeout_s is not None else {})))
        except EmptyResultError:
            return got


@pytest.mark.parametrize('on_error', ['retry', 'skip'])
def test_publish_then_error_delivers_exactly_once_process_pool(tmp_path, on_error):
    from petastorm_tpu.test_util.stub_workers import PublishThenErrorWorker
    pool = ProcessPool(2, on_error=on_error, max_item_retries=2)
    pool.start(PublishThenErrorWorker,
               {'fail_on': (2,), 'state_dir': str(tmp_path)})
    try:
        for i in range(6):
            pool.ventilate(i)
        got = _drain_pool(pool, timeout_s=60)
    finally:
        pool.stop()
        pool.join()
    counts = collections.Counter(got)
    assert sorted(counts) == list(range(6))
    assert all(v == 1 for v in counts.values()), \
        'post-publish error must not re-run the item: {}'.format(counts)
    diag = pool.diagnostics
    assert diag['items_requeued'] == 0 and diag['items_quarantined'] == 0
    assert diag['items_ventilated'] == diag['items_completed'] == 6


@pytest.mark.parametrize('pool_factory', [
    lambda: ThreadPool(2, on_error='retry', max_item_retries=2),
    lambda: DummyPool(on_error='retry', max_item_retries=2),
], ids=['thread', 'dummy'])
def test_publish_then_error_delivers_exactly_once_in_process(tmp_path, pool_factory):
    from petastorm_tpu.test_util.stub_workers import PublishThenErrorWorker
    pool = pool_factory()
    pool.start(PublishThenErrorWorker,
               {'fail_on': (1, 3), 'state_dir': str(tmp_path)})
    for i in range(5):
        pool.ventilate(i)
    got = _drain_pool(pool)
    pool.stop(); pool.join()
    counts = collections.Counter(got)
    assert sorted(counts) == list(range(5))
    assert all(v == 1 for v in counts.values()), \
        'post-publish error must not re-run the item: {}'.format(counts)
    assert pool.diagnostics['items_requeued'] == 0


def test_publish_then_error_raise_policy_still_raises(tmp_path):
    """Under on_error='raise' the historical contract holds: the first
    failure surfaces, delivered payload or not."""
    from petastorm_tpu.test_util.stub_workers import PublishThenErrorWorker
    pool = ThreadPool(1, on_error='raise')
    pool.start(PublishThenErrorWorker,
               {'fail_on': (0,), 'state_dir': str(tmp_path)})
    pool.ventilate(0)
    with pytest.raises(ValueError, match='post-publish failure'):
        _drain_pool(pool)
    pool.stop(); pool.join()


# ---------------------------------------------------------------------------
# recovery events surface through observability
# ---------------------------------------------------------------------------

def test_recovery_counters_and_stall_report(synthetic_dataset, fault_state):
    obs.get_registry().reset()
    faults.install(faults.FaultPlan(error_items=(2,), state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='thread', workers_count=1,
                     output='columnar', seed=0, telemetry='counters',
                     on_error='skip', max_item_retries=0) as reader:
        _drain_ids(reader)
        diag = reader.diagnostics
    report = obs.stall_report(dict(diag, reader_wait_s=1.0))
    assert report['recovery']['items_quarantined'] == 1
    text = obs.format_stall_report(report)
    assert 'recovery events' in text and '1 quarantined' in text


def test_heartbeat_age_gauge_updates(synthetic_dataset):
    obs.get_registry().reset()
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='process', workers_count=1,
                     output='columnar', seed=0, telemetry='counters') as reader:
        _drain_ids(reader)
        diag = reader.diagnostics
        assert 'heartbeat_age_s' in diag
        assert 0 <= diag['heartbeat_age_s'] < 60


# ---------------------------------------------------------------------------
# overhead guards (acceptance: <1% on bench.py; guarded structurally like the
# PR-3 telemetry-off guard, plus a slow-marked timing ratio)
# ---------------------------------------------------------------------------

def test_supervision_overhead_is_per_item_not_per_row():
    """The structural bound: supervision costs one claim + one idle beacon per
    ITEM plus one periodic beacon per heartbeat interval per worker — never
    per-row work. 40 items through a 2-worker pool must stay within that
    message budget (a per-row leak would add hundreds)."""
    from petastorm_tpu.test_util.stub_workers import IdentityWorker
    pool = ProcessPool(2, heartbeat_interval_s=0.5)
    pool.start(IdentityWorker)
    t0 = time.monotonic()
    try:
        for i in range(40):
            pool.ventilate(i)
        got = []
        while True:
            try:
                got.append(pool.get_results(timeout_s=60))
            except EmptyResultError:
                break
        assert sorted(got) == list(range(40))
        elapsed = time.monotonic() - t0
        # one claim beacon per item (the completion message clears it) + the
        # periodic idle beacons + startup slack
        budget = 40 + 2 * (elapsed / 0.5 + 3)
        assert pool._heartbeats_received <= budget, \
            'heartbeat traffic {} exceeds the per-item budget {}'.format(
                pool._heartbeats_received, budget)
        # ownership tracking cleans up after itself: nothing accumulates
        assert pool._inflight == {} and pool._orphans == {}
    finally:
        pool.stop()
        pool.join()


def test_supervision_off_sends_no_heartbeats():
    from petastorm_tpu.test_util.stub_workers import IdentityWorker
    pool = ProcessPool(1, supervision=False)
    pool.start(IdentityWorker)
    try:
        for i in range(5):
            pool.ventilate(i)
        got = []
        while True:
            try:
                got.append(pool.get_results(timeout_s=60))
            except EmptyResultError:
                break
        assert sorted(got) == list(range(5))
        assert pool._heartbeats_received == 0
    finally:
        pool.stop()
        pool.join()


@pytest.mark.slow
def test_supervision_throughput_overhead_under_budget():
    """Timing form of the overhead guard (the <1% budget is asserted with CI
    slack; the structural test above is the regression tripwire): identical
    warm workload — items shaped like real row groups (milliseconds of work,
    not microseconds, matching bench.py's decode items) — with supervision on
    vs off."""
    from petastorm_tpu.test_util.stub_workers import SleepyIdentityWorker

    def run(supervision):
        pool = ProcessPool(2, supervision=supervision)
        pool.start(SleepyIdentityWorker)
        try:
            for i in range(20):  # warm
                pool.ventilate(i, sleep_s=0.005)
            for _ in range(20):
                pool.get_results(timeout_s=60)
            t0 = time.perf_counter()
            for i in range(150):
                pool.ventilate(i, sleep_s=0.005)
            for _ in range(150):
                pool.get_results(timeout_s=60)
            return time.perf_counter() - t0
        finally:
            pool.stop()
            pool.join()

    on, off = run(True), run(False)
    assert on <= off * 1.1, 'supervision overhead {:.1%} exceeds budget'.format(on / off - 1)


# ---------------------------------------------------------------------------
# graceful degradation: respawn failure sheds the slot, fails at zero workers
# ---------------------------------------------------------------------------

def test_respawn_failure_sheds_slot_and_depletes_pool():
    """When respawn itself fails the pool degrades (slot shed, loud error)
    rather than crash-looping, and only a fully-shed pool raises
    WorkerPoolDepletedError."""
    from petastorm_tpu.errors import WorkerPoolDepletedError
    from petastorm_tpu.test_util.stub_workers import HardExitWorker
    pool = ProcessPool(1)
    pool.start(HardExitWorker, {'crash_on': 1})
    try:
        pool.ventilate(0)
        assert pool.get_results(timeout_s=60) == [0]

        def broken_spawn(worker_id, ring_name):
            raise OSError('simulated: fork/exec failed')

        pool._spawn_worker = broken_spawn
        pool.ventilate(1)  # kills the only worker; its respawn now fails
        with pytest.raises(WorkerPoolDepletedError, match='respawn kept failing'):
            while True:
                pool.get_results(timeout_s=60)
        assert pool._all_slots_shed()
    finally:
        pool.stop()
        pool.join()


# ---------------------------------------------------------------------------
# thread-pool exactly-once accounting under requeue (no reader involved)
# ---------------------------------------------------------------------------

def test_thread_pool_retry_accounting_exact():
    from petastorm_tpu.test_util.stub_workers import ExceptionEveryNWorker
    pool = ThreadPool(1, on_error='skip', max_item_retries=1)
    pool.start(ExceptionEveryNWorker, worker_setup_args=5)  # value % 5 == 0 fails
    for i in [1, 2, 5, 3]:
        pool.ventilate(i)
    got = []
    while True:
        try:
            got.append(pool.get_results())
        except EmptyResultError:
            break
    assert sorted(got) == [1, 2, 3]
    diag = pool.diagnostics
    assert diag['items_ventilated'] == diag['items_completed'] == 4
    assert diag['items_requeued'] == 1 and diag['items_quarantined'] == 1
    assert len(pool.quarantined_items) == 1
    pool.stop(); pool.join()


def test_dummy_pool_skip_does_not_stop_epoch():
    from petastorm_tpu.test_util.stub_workers import ExceptionEveryNWorker
    pool = DummyPool(on_error='skip', max_item_retries=0)
    pool.start(ExceptionEveryNWorker, worker_setup_args=2)
    for i in [1, 2, 3, 4, 5]:
        pool.ventilate(i)
    got = []
    while True:
        try:
            got.append(pool.get_results())
        except EmptyResultError:
            break
    assert sorted(got) == [1, 3, 5]
    assert pool.diagnostics['items_quarantined'] == 2
    assert pool.diagnostics['items_completed'] == 5
    pool.stop(); pool.join()
