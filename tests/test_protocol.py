"""Worker-pool protocol verifier (petastorm_tpu/analysis/protocol/).

Four layers (docs/protocol.md):

* **Spec unit tests** — transition-system sanity, canonical state hashing
  (slot symmetry, item renaming, dispatch-id renumbering), replay helpers.
* **Model checker** — small scopes exhaust clean; every seeded spec mutation
  yields a minimized counterexample; trace minimization actually shrinks;
  the ``petastorm-tpu-modelcheck`` CLI honors its exit-code contract.
* **THE tier-1 gate** — the default small-scope configuration (3 workers,
  4 items, 2 crashes) exhausts within an explicit wall-clock budget with a
  state-count floor, proving all five invariants; a budget overrun or a
  degenerated search fails loudly.
* **Runtime monitor** — accepts every legal schedule (seeded random walks
  replayed through the spec's observer projection; hypothesis-driven when
  hypothesis is installed), rejects each mutation counterexample and a
  catalog of crafted violations, and conforms on real pools (the
  fault-tolerance suite runs every crash/requeue/poison scenario with the
  monitor attached — see tests/test_fault_tolerance.py).
"""

import subprocess
import sys
import time

import pytest

from petastorm_tpu.analysis.protocol import modelcheck as M
from petastorm_tpu.analysis.protocol import spec as S
from petastorm_tpu.analysis.protocol.monitor import ProtocolMonitor
from petastorm_tpu.errors import EmptyResultError, ProtocolViolation

TINY = dict(workers=2, items=2, crashes=1)


def _check(mutation=None, **kw):
    cfg = S.SpecConfig(mutation=mutation, **dict(TINY, **kw))
    return M.check(cfg, budget_s=120)


# ---------------------------------------------------------------------------
# spec: states, transitions, canonicalization
# ---------------------------------------------------------------------------

def test_initial_state_shape():
    cfg = S.SpecConfig(**TINY)
    st = S.initial_state(cfg)
    assert st[S.NEXT_ITEM] == 0 and st[S.NEXT_D] == 0
    assert len(st[S.SLOTS]) == cfg.workers
    assert all(s[S.S_ALIVE] for s in st[S.SLOTS])
    assert S.check_state(st, cfg) is None


def test_successors_from_init_are_dispatches():
    cfg = S.SpecConfig(**TINY)
    succ = S.successors(S.initial_state(cfg), cfg)
    kinds = {label[0] for label, _ in succ}
    # only dispatch and (budget permitting) idle crashes are enabled at start
    assert kinds <= {'dispatch', 'crash'}
    assert 'dispatch' in kinds


def test_canonicalize_slot_symmetry():
    cfg = S.SpecConfig(**TINY)
    st = S.initial_state(cfg)
    slot_busy = (1, S.WORK, 0, (), (), -1)
    slot_idle = st[S.SLOTS][0]
    a = st[:S.SLOTS] + ((slot_busy, slot_idle),) + st[S.SLOTS + 1:]
    b = st[:S.SLOTS] + ((slot_idle, slot_busy),) + st[S.SLOTS + 1:]
    assert S.canonicalize(a, cfg) == S.canonicalize(b, cfg)


def test_canonicalize_item_symmetry():
    """Two dispatched items with identical accounting signatures collapse
    regardless of which index completed first."""
    cfg = S.SpecConfig(**TINY)
    st = S.initial_state(cfg)
    st = st[:S.NEXT_ITEM] + (2,) + st[S.NEXT_ITEM + 1:]
    a = S._set(S._set(st, S.COMPLETED, (1, 0)), S.DELIVERED, (1, 0))
    a = S._set(a, S.COMPLETED_ITEMS, 1)
    b = S._set(S._set(st, S.COMPLETED, (0, 1)), S.DELIVERED, (0, 1))
    b = S._set(b, S.COMPLETED_ITEMS, 1)
    assert S.canonicalize(a, cfg) == S.canonicalize(b, cfg)


def test_canonicalize_renumbers_dispatch_ids():
    """States whose requeue histories burned different id counts are the same
    canonical state (bisimulation quotient) — but NOT for mutated specs,
    where trace/monitor id stability wins."""
    cfg = S.SpecConfig(**TINY)
    st = S.initial_state(cfg)
    st = S._set(S._set(st, S.NEXT_ITEM, 1), S.NEXT_D, 9)
    lo = S._set(st, S.INFLIGHT, ((2, 0, 0, 0),))
    hi = S._set(st, S.INFLIGHT, ((7, 0, 0, 0),))
    assert S.canonicalize(lo, cfg) == S.canonicalize(hi, cfg)
    mcfg = S.SpecConfig(mutation='requeue_same_id', **TINY)
    assert S.canonicalize(lo, mcfg) != S.canonicalize(hi, mcfg)


def test_replay_trace_validates_labels():
    cfg = S.SpecConfig(**TINY)
    trace, _final = M.random_walk(cfg, seed=7, max_steps=40)
    assert trace
    # canonical replay accepts the canonical re-recording of a legal schedule
    state = S.canonicalize(S.initial_state(cfg), cfg)
    canonical_trace = []
    for _ in range(10):
        succ = S.successors(state, cfg)
        if not succ:
            break
        label, state = succ[0]
        canonical_trace.append(label)
    S.replay_trace(cfg, canonical_trace)
    with pytest.raises(ProtocolViolation, match='not enabled'):
        S.replay_trace(cfg, [('pickup', 0, 99)])


# ---------------------------------------------------------------------------
# model checker: clean scopes, mutations, minimization, CLI
# ---------------------------------------------------------------------------

def test_tiny_scope_exhausts_clean():
    result = _check()
    assert result.exhausted and result.violation is None
    assert result.states > 1_000  # the space is real, not degenerate
    assert result.terminal_states >= 1


def test_error_scope_exhausts_clean():
    """Worker-raised errors (retry -> quarantine lattice) on top of crashes."""
    result = M.check(S.SpecConfig(**M.ERROR_SCOPE), budget_s=120)
    assert result.exhausted and result.violation is None


@pytest.mark.parametrize('policy', ['raise', 'retry'])
def test_other_policies_exhaust_clean(policy):
    result = _check(policy=policy, errors=1)
    assert result.exhausted and result.violation is None


@pytest.mark.parametrize('mutation,invariant', [
    ('requeue_same_id', 'exactly_once_delivery'),
    ('requeue_published', 'exactly_once_delivery'),
    ('no_stale_drop', 'no_double_count'),
    ('no_drain_before_respawn', 'epoch_termination'),
])
def test_mutations_yield_minimized_counterexamples(mutation, invariant):
    """Each seeded protocol defect is caught, with a minimized trace that
    replays through the spec to the violating state — the checker has teeth
    (the ISSUE acceptance example is requeue_same_id: requeue without a fresh
    dispatch id)."""
    result = _check(mutation=mutation, errors=1)
    assert result.violation == invariant
    assert result.trace, 'a counterexample must carry its trace'
    cfg = S.SpecConfig(mutation=mutation, errors=1, **TINY)
    assert M._trace_violates(cfg, result.trace, invariant)
    # minimal: removing ANY single step breaks the reproduction
    for i in range(len(result.trace)):
        assert not M._trace_violates(cfg, result.trace[:i] + result.trace[i + 1:],
                                     invariant)


def test_minimize_trace_strips_padding():
    """A counterexample artificially padded with an unrelated item's full
    lifecycle shrinks back to (at most) its original length."""
    cfg = S.SpecConfig(mutation='requeue_published', errors=1, **TINY)
    result = M.check(cfg, budget_s=120)
    minimal = result.trace
    padded = list(minimal)
    # grow a longer valid trace by taking extra enabled steps first, then
    # checking the original still replays; find a prefix extension that works
    state = S.canonicalize(S.initial_state(cfg), cfg)
    extra = []
    for label, ns in S.successors(state, cfg):
        if label[0] == 'dispatch' and label != minimal[0]:
            extra = [label]
            break
    if extra and M._trace_violates(cfg, extra + padded, result.violation):
        out = M.minimize_trace(cfg, extra + padded, result.violation)
        assert len(out) <= len(minimal)


def test_format_trace_is_readable():
    result = _check(mutation='requeue_same_id', errors=1)
    text = M.format_trace(result)
    assert 'counterexample' in text and 'exactly_once_delivery' in text
    assert 'dispatch item=' in text


def test_cli_exit_code_contract(tmp_path):
    base = [sys.executable, '-m', 'petastorm_tpu.analysis.protocol.modelcheck']
    clean = subprocess.run(base + ['--workers', '2', '--items', '2', '--crashes', '1',
                                   '--budget-s', '120'],
                           capture_output=True, text=True, timeout=300)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert 'exhausted: all invariants hold' in clean.stdout

    bad = subprocess.run(base + ['--workers', '2', '--items', '2', '--crashes', '1',
                                 '--errors', '1', '--mutate', 'requeue_same_id'],
                         capture_output=True, text=True, timeout=300)
    assert bad.returncode == 1
    assert 'counterexample' in bad.stdout

    usage = subprocess.run(base + ['--workers', '1', '--items', '1', '--crashes', '3'],
                           capture_output=True, text=True, timeout=120)
    assert usage.returncode == 2

    floor = subprocess.run(base + ['--workers', '2', '--items', '1', '--crashes', '1',
                                   '--min-states', '99999999'],
                           capture_output=True, text=True, timeout=300)
    assert floor.returncode == 3
    assert 'below the declared floor' in floor.stderr


def test_console_script_target_resolves():
    import importlib
    func = getattr(importlib.import_module(
        'petastorm_tpu.analysis.protocol.modelcheck'), 'main')
    assert callable(func)


# ---------------------------------------------------------------------------
# THE tier-1 gate: default small scope, budgeted, with a state-count floor
# ---------------------------------------------------------------------------

#: wall budget for the default scope — ~2.5x the uncontended runtime so a
#: loaded CI host cannot flake it, while a genuine blowup still fails
TIER1_BUDGET_S = 300


def test_default_scope_exhausts_within_budget_and_floor():
    """The acceptance gate: the default configuration (>=3 workers, >=4 items,
    >=2 injected crashes) is EXHAUSTED — every reachable interleaving visited
    — within the declared budget, the reported state count clears the
    declared floor (so the search cannot silently degenerate), and all five
    invariants hold."""
    cfg = S.SpecConfig(**M.DEFAULT_SCOPE)
    assert cfg.workers >= 3 and cfg.items >= 4 and cfg.crashes >= 2
    t0 = time.monotonic()
    result = M.check(cfg, budget_s=TIER1_BUDGET_S)
    elapsed = time.monotonic() - t0
    assert result.exhausted, \
        'default scope not exhausted in {:.0f}s ({} states)'.format(
            elapsed, result.states)
    assert result.violation is None, M.format_trace(result)
    assert result.states >= M.DEFAULT_STATE_FLOOR, \
        'state count {} under the floor {} — the exhaustive search ' \
        'degenerated'.format(result.states, M.DEFAULT_STATE_FLOOR)
    assert result.terminal_states >= 1
    assert elapsed <= TIER1_BUDGET_S + 5


# ---------------------------------------------------------------------------
# runtime monitor: event rules
# ---------------------------------------------------------------------------

def test_monitor_accepts_the_happy_path():
    m = ProtocolMonitor()
    m.on_dispatch(0, seq=10)
    m.on_message('claim', 0)
    m.on_message('data', 0, live=True)
    m.on_message('done', 0, live=True)
    m.on_complete(0, delivered=True)
    m.on_drained(1, 1)
    assert m.snapshot['in_flight'] == []


def test_monitor_accepts_requeue_and_stale_drop():
    m = ProtocolMonitor()
    m.on_dispatch(0)
    m.on_message('claim', 0)
    m.on_requeue(0, 1)                    # crash recovery path
    m.on_message('done', 0, live=False)   # straggler from the dead attempt
    m.on_message('data', 1, live=True)
    m.on_complete(1, delivered=True)
    m.on_drained(1, 1)


def test_monitor_rejects_reused_dispatch_id():
    m = ProtocolMonitor()
    m.on_dispatch(0)
    with pytest.raises(ProtocolViolation, match='reuses dispatch id'):
        m.on_dispatch(0)
    m2 = ProtocolMonitor()
    m2.on_dispatch(0)
    with pytest.raises(ProtocolViolation, match='reuses dispatch id'):
        m2.on_requeue(0, 0)


def test_monitor_rejects_unknown_id_and_misclassification():
    m = ProtocolMonitor()
    m.on_dispatch(0)
    with pytest.raises(ProtocolViolation, match='never issued'):
        m.on_message('done', 5, live=True)
    m2 = ProtocolMonitor()
    m2.on_dispatch(0)
    m2.on_requeue(0, 1)
    with pytest.raises(ProtocolViolation, match='retired dispatch id'):
        m2.on_message('done', 0, live=True)   # stale treated as live
    m3 = ProtocolMonitor()
    m3.on_dispatch(0)
    with pytest.raises(ProtocolViolation, match='dropped a .* live'):
        m3.on_message('done', 0, live=False)  # live dropped as stale


def test_monitor_rejects_double_completion():
    m = ProtocolMonitor()
    m.on_dispatch(0)
    m.on_complete(0, delivered=True)
    with pytest.raises(ProtocolViolation, match='not in flight'):
        m.on_complete(0, delivered=True)
    # ...even through a requeue chain: the LOGICAL item completed twice
    m2 = ProtocolMonitor()
    m2.on_dispatch(0)
    m2.on_requeue(0, 1)
    m2.on_dispatch(2)
    m2.on_complete(1, delivered=True)
    m2.on_requeue(2, 3)
    m2.on_complete(3, delivered=True)
    assert m2.completed == 2


def test_monitor_rejects_requeue_after_delivery():
    """The requeue_published defect at runtime: requeueing an item whose
    payload already reached the consumer guarantees double delivery."""
    m = ProtocolMonitor()
    m.on_dispatch(0)
    m.on_message('data', 0, live=True)
    with pytest.raises(ProtocolViolation, match='delivered'):
        m.on_requeue(0, 1)


def test_monitor_rejects_diverged_drain():
    m = ProtocolMonitor()
    m.on_dispatch(0)
    with pytest.raises(ProtocolViolation, match='still in flight'):
        m.on_drained(1, 1)
    m2 = ProtocolMonitor()
    m2.on_dispatch(0)
    m2.on_complete(0, delivered=True)
    with pytest.raises(ProtocolViolation, match='diverge'):
        m2.on_drained(5, 5)


# ---------------------------------------------------------------------------
# randomized schedules: spec traces replayed through the monitor
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('seed', range(25))
def test_random_schedules_conform(seed):
    """Soundness: the monitor accepts every legal schedule. Seeded random
    walks through the spec (crashes, errors, sweeps, stale straggler drops
    included) replay through the monitor without a violation, and the walk's
    final state satisfies every safety invariant."""
    cfg = S.SpecConfig(workers=2, items=3, crashes=1, errors=1, retries=1,
                       policy='skip')
    trace, final = M.random_walk(cfg, seed=seed)
    assert S.check_state(final, cfg) is None
    S.replay_into_monitor(trace, ProtocolMonitor(name='walk-{}'.format(seed)))


def test_random_schedules_conform_hypothesis():
    """The same property under hypothesis when available (the container may
    not ship it; the seeded sweep above always runs)."""
    hypothesis = pytest.importorskip('hypothesis')
    from hypothesis import strategies as st

    @hypothesis.given(st.integers(min_value=0, max_value=10_000))
    @hypothesis.settings(max_examples=50, deadline=None)
    def prop(seed):
        cfg = S.SpecConfig(workers=2, items=2, crashes=1, errors=1)
        trace, final = M.random_walk(cfg, seed=seed, max_steps=300)
        assert S.check_state(final, cfg) is None
        S.replay_into_monitor(trace, ProtocolMonitor())

    prop()


@pytest.mark.parametrize('mutation', ['requeue_same_id', 'requeue_published',
                                      'no_stale_drop'])
def test_mutation_counterexamples_are_rejected_by_monitor(mutation):
    """Teeth: the event sequence of each mutation's minimized counterexample
    is rejected by the runtime monitor — what the model checker catches in
    the spec, the monitor catches in a live pool."""
    result = _check(mutation=mutation, errors=1)
    assert result.trace
    with pytest.raises(ProtocolViolation):
        S.replay_into_monitor(result.trace, ProtocolMonitor(name=mutation))


# ---------------------------------------------------------------------------
# monitor on real pools (cheap in-process checks; the full crash matrix runs
# monitor-enabled in tests/test_fault_tolerance.py)
# ---------------------------------------------------------------------------

def _drain(pool):
    got = []
    while True:
        try:
            got.append(pool.get_results())
        except EmptyResultError:
            return got


def test_thread_pool_conforms_under_retry_policy():
    from petastorm_tpu.test_util.stub_workers import ExceptionEveryNWorker
    from petastorm_tpu.workers import ThreadPool
    pool = ThreadPool(2, on_error='skip', max_item_retries=1, protocol_monitor=True)
    pool.start(ExceptionEveryNWorker, worker_setup_args=3)
    for i in [1, 2, 3, 4, 5]:
        pool.ventilate(i)
    got = _drain(pool)
    pool.stop(); pool.join()
    assert sorted(got) == [1, 2, 4, 5]
    snap = pool.protocol_monitor.snapshot
    assert snap['ventilated'] == snap['completed'] == 5
    assert snap['in_flight'] == []


def test_dummy_pool_conforms_and_env_var_opt_in(monkeypatch):
    from petastorm_tpu.test_util.stub_workers import IdentityWorker
    from petastorm_tpu.workers import DummyPool
    monkeypatch.setenv('PSTPU_PROTOCOL_MONITOR', '1')
    pool = DummyPool()
    assert pool.protocol_monitor is not None, 'env var must arm the monitor'
    pool.start(IdentityWorker)
    for i in range(4):
        pool.ventilate(i)
    assert sorted(_drain(pool)) == list(range(4))
    pool.stop(); pool.join()
    monkeypatch.setenv('PSTPU_PROTOCOL_MONITOR', '0')
    assert DummyPool().protocol_monitor is None


def test_process_pool_protocol_echo_worker():
    """A spawned worker resolves the SAME canonical protocol module as the
    supervisor (the single-definition-site property PT801 enforces in
    source)."""
    from petastorm_tpu.test_util.stub_workers import ProtocolEchoWorker
    from petastorm_tpu.workers import ProcessPool
    from petastorm_tpu.workers import protocol
    pool = ProcessPool(1, protocol_monitor=True)
    pool.start(ProtocolEchoWorker)
    try:
        pool.ventilate(0)
        item, kinds, header_len = pool.get_results(timeout_s=60)
        assert kinds == sorted(protocol.MESSAGE_KINDS.values())
        assert header_len == protocol.RING_HEADER_LEN
    finally:
        pool.stop()
        pool.join()
