"""First-party zero-copy Parquet page scan (native/pagescan.py +
pstpu_scan_plain_pages in rowgroup_reader.cpp).

The scan replaces Arrow's assemble-and-copy decode with views over the
mmapped file for UNCOMPRESSED PLAIN REQUIRED fixed-width columns — the
RawTensorCodec training-store layout. These tests pin: byte equality with the
Arrow path, the end-to-end reader on scanned stores, backward compatibility
with pre-round-5 (variable binary) stores, and the fallbacks (compression,
nullable, dictionary) that must silently route to Arrow."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField

native = pytest.importorskip('petastorm_tpu.native')
pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason='native kernel unavailable')


def _raw_schema(image_size=8):
    return Unischema('Raw', [
        UnischemaField('image', np.uint8, (image_size, image_size, 3),
                       RawTensorCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(np.int64), False),
        UnischemaField('weight', np.float32, (), ScalarCodec(np.float32), False),
    ])


def _write_raw_store(tmp_path, rows=24, image_size=8, compression='none'):
    schema = _raw_schema(image_size)
    url = 'file://' + str(tmp_path / 'raw')
    rng = np.random.default_rng(0)
    data = [{'image': rng.integers(0, 255, (image_size, image_size, 3), np.uint8),
             'label': int(i % 5), 'weight': float(i) * 0.5} for i in range(rows)]
    write_petastorm_dataset(url, schema, iter(data), rows_per_row_group=8,
                            compression=compression)
    return url, data


def _parquet_path(tmp_path):
    root = tmp_path / 'raw'
    return str(next(p for p in root.iterdir() if p.suffix == '.parquet'))


def test_raw_store_layout_is_scannable(tmp_path):
    """The writer must produce the exact layout the scanner serves: FLBA /
    plain numeric, UNCOMPRESSED, PLAIN, dictionary-free, REQUIRED, one page
    per row group."""
    _write_raw_store(tmp_path)
    md = pq.read_metadata(_parquet_path(tmp_path))
    for i in range(md.num_columns):
        col = md.row_group(0).column(i)
        assert col.compression == 'UNCOMPRESSED'
        assert not col.has_dictionary_page
        assert 'PLAIN' in col.encodings and 'PLAIN_DICTIONARY' not in col.encodings
        assert md.schema.column(i).max_definition_level == 0
    assert md.row_group(0).column(0).physical_type == 'FIXED_LEN_BYTE_ARRAY'


def test_scanned_table_matches_arrow_path(tmp_path, monkeypatch):
    url, _ = _write_raw_store(tmp_path)
    path = _parquet_path(tmp_path)
    fast = native.NativeParquetFile(path)
    cols = ['image', 'label', 'weight']
    t_fast = fast.read_row_group(1, columns=cols)
    assert set(fast._zerocopy_columns(1, cols)) == set(cols)  # all served zero-copy
    monkeypatch.setenv('PSTPU_DISABLE_PAGESCAN', '1')
    t_ref = native.NativeParquetFile(path).read_row_group(1, columns=cols)
    assert t_fast.num_rows == t_ref.num_rows == 8
    for c in cols:
        a = t_fast.column(c).combine_chunks()
        b = t_ref.column(c).combine_chunks().cast(a.type)
        assert a.equals(b), c


def test_end_to_end_reader_on_scanned_store(tmp_path):
    url, data = _write_raw_store(tmp_path)
    with make_reader(url, reader_pool_type='thread', workers_count=2,
                     shuffle_row_groups=False) as reader:
        rows = {i: r for i, r in enumerate(reader)}
    assert len(rows) == len(data)
    by_weight = {float(r.weight): r for r in rows.values()}
    for d in data:
        got = by_weight[d['weight']]
        np.testing.assert_array_equal(got.image, d['image'])
        assert int(got.label) == d['label']


def test_columnar_block_is_mmap_view(tmp_path):
    """The decoded image block must be a VIEW (zero copy), not a fresh buffer
    — the entire point of the scan."""
    url, data = _write_raw_store(tmp_path)
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        block = next(iter(reader))
    img = np.asarray(block.image)
    assert img.base is not None  # a view chain, not an owning allocation
    np.testing.assert_array_equal(img[0], data[0]['image'])


def test_compressed_store_falls_back_to_arrow(tmp_path):
    url, data = _write_raw_store(tmp_path, compression='snappy')
    md = pq.read_metadata(_parquet_path(tmp_path))
    assert md.row_group(0).column(1).compression == 'SNAPPY'  # label compressed
    nf = native.NativeParquetFile(_parquet_path(tmp_path))
    assert nf._zerocopy_columns(0, ['label']) == {}
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False) as reader:
        got = sorted(int(r.label) for r in reader)
    assert got == sorted(d['label'] for d in data)


def _nullable_store(tmp_path, rows):
    schema = Unischema('N', [
        UnischemaField('x', np.float32, (4,), RawTensorCodec(), True),
        UnischemaField('id', np.int64, (), ScalarCodec(np.int64), False),
    ])
    url = 'file://' + str(tmp_path / 'raw')
    write_petastorm_dataset(url, schema, iter(rows), rows_per_row_group=3,
                            compression='none')
    md = pq.read_metadata(_parquet_path(tmp_path))
    x_idx = [i for i in range(md.num_columns) if md.schema.column(i).path == 'x'][0]
    assert md.schema.column(x_idx).max_definition_level == 1
    return url


def test_nullable_column_without_nulls_served_via_def_skip(tmp_path):
    """OPTIONAL columns the statistics prove null-free ride the scan too —
    their RLE def-levels block is skipped (nullable-by-default writers are
    the common real-world layout)."""
    rows = [{'x': np.arange(4, dtype=np.float32) + i, 'id': i} for i in range(6)]
    url = _nullable_store(tmp_path, rows)
    nf = native.NativeParquetFile(_parquet_path(tmp_path))
    assert 'x' in nf._zerocopy_columns(0, ['x', 'id'])
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False) as r:
        got = {int(row.id): row.x for row in r}
    for row in rows:
        np.testing.assert_array_equal(got[row['id']], row['x'])


def test_nullable_column_with_actual_nulls_falls_back(tmp_path):
    """A real null desynchronizes a def-skipped values region — statistics
    with null_count > 0 must route the column to the Arrow path."""
    rows = [{'x': None if i == 2 else np.arange(4, dtype=np.float32) + i, 'id': i}
            for i in range(6)]
    url = _nullable_store(tmp_path, rows)
    nf = native.NativeParquetFile(_parquet_path(tmp_path))
    assert 'x' not in nf._zerocopy_columns(0, ['x', 'id'])
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False) as r:
        got = {int(row.id): row.x for row in r}
    assert got[2] is None
    np.testing.assert_array_equal(got[4], rows[4]['x'])


def test_pre_round5_binary_store_still_decodes(tmp_path, monkeypatch):
    """Stores written when RawTensorCodec used variable-width binary (rounds
    2-4) must keep decoding — both per-cell and whole-column paths."""
    monkeypatch.setattr(RawTensorCodec, 'arrow_type', lambda self, field: pa.binary())
    url, data = _write_raw_store(tmp_path)
    monkeypatch.undo()
    md = pq.read_metadata(_parquet_path(tmp_path))
    assert md.row_group(0).column(0).physical_type == 'BYTE_ARRAY'
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        images = np.concatenate([np.asarray(b.image) for b in reader])
    np.testing.assert_array_equal(images[3], data[3]['image'])


def test_process_pool_ships_mmap_view_blocks(tmp_path):
    """Read-only mmap-view blocks must survive the process-pool transport
    (writev reads straight from the views' memory)."""
    url, data = _write_raw_store(tmp_path)
    with make_reader(url, reader_pool_type='process', workers_count=1,
                     output='columnar', shuffle_row_groups=False,
                     num_epochs=1) as reader:
        blocks = list(reader)
    images = np.concatenate([np.asarray(b.image) for b in blocks])
    labels = np.concatenate([np.asarray(b.label) for b in blocks])
    assert len(images) == len(data)
    order = np.argsort([d['weight'] for d in data])  # written order preserved
    np.testing.assert_array_equal(images[0], data[0]['image'])
    assert labels.tolist() == [d['label'] for d in data]
    assert images[5].flags.writeable  # transport restores the writable contract


def test_partition_only_projection_keeps_rows(tmp_path):
    """schema_fields=[partition key] reads NO physical columns — the Arrow
    path's 0-column N-row table supplies the row counts, and the fast-only
    return must not swallow it (review r5 regression: returned 0 rows)."""
    schema = Unischema('P', [
        UnischemaField('pk', np.str_, (), ScalarCodec(), False),
        UnischemaField('x', np.float32, (2,), RawTensorCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'raw')
    write_petastorm_dataset(
        url, schema,
        ({'pk': 'p{}'.format(i % 2), 'x': np.full(2, i, np.float32)} for i in range(8)),
        rows_per_row_group=2, partition_by=['pk'], compression='none')
    with make_reader(url, reader_pool_type='dummy', schema_fields=['pk'],
                     shuffle_row_groups=False) as reader:
        vals = sorted(row.pk for row in reader)
    assert vals == ['p0'] * 4 + ['p1'] * 4


def test_decode_column_empty_chunked_returns_none():
    """0-chunk FSB columns must route to the per-cell fallback, not crash in
    np.concatenate (review r5 regression)."""
    codec = RawTensorCodec()
    field = UnischemaField('x', np.float32, (2,), codec, False)
    assert codec.decode_column(field, pa.chunked_array([], type=pa.binary(8))) is None


def test_plain_parquet_store_served_by_scan(tmp_path):
    """make_batch_reader over a PLAIN uncompressed non-petastorm store rides
    the same fast path: the batch worker opens files through the identical
    NativeParquetFile, so dictionary-free numeric columns of ordinary Parquet
    serve zero-copy too."""
    from petastorm_tpu import make_batch_reader
    path = tmp_path / 'plain'
    path.mkdir()
    table = pa.table({'x': pa.array(np.arange(50, dtype=np.int64)),
                      'y': pa.array(np.linspace(0, 1, 50).astype(np.float64))})
    pq.write_table(table, str(path / 'f.parquet'), compression='none',
                   use_dictionary=False)
    nf = native.NativeParquetFile(str(path / 'f.parquet'))
    assert set(nf._zerocopy_columns(0, ['x', 'y'])) == {'x', 'y'}
    url = 'file://' + str(path)
    with make_batch_reader(url, reader_pool_type='dummy',
                           shuffle_row_groups=False) as reader:
        xs, ys = [], []
        for b in reader:
            xs.extend(b.x.tolist())
            ys.extend(b.y.tolist())
    assert xs == list(range(50))
    np.testing.assert_allclose(ys, np.linspace(0, 1, 50))


def test_qualification_rejects_repeated_columns():
    """Legacy top-level `repeated` primitives have max_def_level 1, a
    dot-free path AND possibly null_count==0 stats — but their pages lead
    with a repetition-levels block the scanner does not skip. Any repetition
    must disqualify (review r5 finding: silent value shift otherwise)."""
    import types

    from petastorm_tpu.native import pagescan

    meta = types.SimpleNamespace(
        compression='UNCOMPRESSED', encodings=('PLAIN', 'RLE'),
        has_dictionary_page=False, physical_type='INT64',
        statistics=types.SimpleNamespace(null_count=0))
    assert pagescan._column_qualifies(meta, 0, 0) is True
    assert pagescan._column_qualifies(meta, 1, 0) == 'def'
    assert pagescan._column_qualifies(meta, 1, 1) is False  # repeated: reject
    assert pagescan._column_qualifies(meta, 0, 1) is False


def test_scanner_rejects_garbage_chunk():
    lib = native._load_library()
    import ctypes
    junk = (ctypes.c_uint8 * 64)(*([0xFF] * 64))
    offs = (ctypes.c_ulonglong * 8)()
    counts = (ctypes.c_longlong * 8)()
    vlens = (ctypes.c_ulonglong * 8)()
    assert lib.pstpu_scan_plain_pages(junk, 64, offs, counts, vlens, 8, 0) == -1


def test_page_values_must_fit_page_region(tmp_path):
    """A page's zero-copy view must be bounds-checked against the PAGE's
    values region, not just the file: a value count inflated by a wrong
    statistic or corrupt header would otherwise serve the NEXT page's header
    bytes as tensor data (ADVICE r5 finding)."""
    from petastorm_tpu.native import pagescan

    _write_raw_store(tmp_path)
    path = _parquet_path(tmp_path)
    md = pq.read_metadata(path)
    rg = md.row_group(0)
    label_idx = [i for i in range(md.num_columns)
                 if md.schema.column(i).path == 'label'][0]
    col = rg.column(label_idx)
    lib = native._load_library()
    mm = np.memmap(path, dtype=np.uint8, mode='r')
    pages = pagescan._scan_chunk(lib, mm, col)
    assert pages
    # the scanner-reported region length matches the real layout exactly
    # (REQUIRED PLAIN int64: count * 8 bytes fills the page)
    assert all(count * 8 == vlen for _off, count, vlen in pages)
    good = pagescan._chunk_to_arrays(mm, col, pages, rg.num_rows, 0)
    assert good is not None
    # inflated count -> values overrun the page region -> Arrow fallback
    over = [(off, count + 1, vlen) for off, count, vlen in pages]
    assert pagescan._chunk_to_arrays(
        mm, col, over, rg.num_rows + len(pages), 0) is None
    # short values region on a REQUIRED column (require_exact) -> fallback
    short = [(off, count - 1, vlen) for off, count, vlen in pages]
    assert pagescan._chunk_to_arrays(
        mm, col, short, rg.num_rows - len(pages), 0) is None
    # a def-skipped OPTIONAL column may leave a region tail (require_exact off)
    assert pagescan._chunk_to_arrays(
        mm, col, short, rg.num_rows - len(pages), 0, require_exact=False) is not None


def test_deeply_nested_page_header_fails_fast_not_stack_overflow():
    """A corrupt/hostile thrift page header nesting structs thousands of
    levels deep must hit the skipper's depth cap and return -1 (Arrow
    fallback) — pre-fix, the unbounded recursion overflowed the C++ stack
    and killed the process (ADVICE r5 finding)."""
    lib = native._load_library()
    import ctypes
    # field id 6 / type struct opens the chain; each 0x1C byte nests one more
    # struct field — 200k levels would need ~200k stack frames without the cap
    payload = bytes([0x6C]) + b'\x1c' * 200000
    buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
    offs = (ctypes.c_ulonglong * 8)()
    counts = (ctypes.c_longlong * 8)()
    vlens = (ctypes.c_ulonglong * 8)()
    assert lib.pstpu_scan_plain_pages(
        buf, len(payload), offs, counts, vlens, 8, 0) == -1
