"""Write path + metadata tests (modeled on reference etl tests)."""

import json

import numpy as np
import pyarrow.parquet as pq
import pytest

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import (PetastormMetadataError, get_schema,
                                                infer_or_load_unischema, load_row_groups,
                                                materialize_dataset, read_metadata_value,
                                                write_petastorm_dataset, ROW_GROUPS_PER_FILE_KEY)
from petastorm_tpu.fs import FilesystemResolver, path_to_url
from petastorm_tpu.unischema import Unischema, UnischemaField


def _small_schema():
    return Unischema('Small', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
    ])


def _rows(n):
    return [{'id': i, 'vec': np.full(4, i, dtype=np.float32)} for i in range(n)]


def test_write_and_load_row_groups(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    write_petastorm_dataset(url, _small_schema(), _rows(25), rows_per_row_group=10)
    pieces = load_row_groups(url)
    assert len(pieces) == 3  # 10 + 10 + 5
    schema = get_schema(url)
    assert list(schema.fields) == ['id', 'vec']


def test_rows_per_file_splits_files(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    write_petastorm_dataset(url, _small_schema(), _rows(30), rows_per_row_group=5, rows_per_file=10)
    pieces = load_row_groups(url)
    assert len(pieces) == 6
    assert len({p.path for p in pieces}) == 3


def test_row_group_counts_metadata_written(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    write_petastorm_dataset(url, _small_schema(), _rows(20), rows_per_row_group=10)
    raw = read_metadata_value(url, ROW_GROUPS_PER_FILE_KEY)
    counts = json.loads(raw.decode())
    assert sum(len(v) for v in counts.values()) == 2
    # fast path populates per-piece row counts too
    pieces = load_row_groups(url)
    assert [p.num_rows for p in pieces] == [10, 10]


def test_load_row_groups_footer_fallback(tmp_path):
    """Without _common_metadata, fall back to parallel footer reads."""
    url = path_to_url(tmp_path / 'ds')
    write_petastorm_dataset(url, _small_schema(), _rows(25), rows_per_row_group=10)
    (tmp_path / 'ds' / '_common_metadata').unlink()
    pieces = load_row_groups(url)
    assert len(pieces) == 3
    assert all(p.num_rows in (10, 5) for p in pieces)


def test_get_schema_missing_metadata_raises(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    write_petastorm_dataset(url, _small_schema(), _rows(5), rows_per_row_group=5)
    (tmp_path / 'ds' / '_common_metadata').unlink()
    with pytest.raises(PetastormMetadataError):
        get_schema(url)


def test_infer_schema_plain_parquet(scalar_dataset):
    schema = infer_or_load_unischema(scalar_dataset.url)
    assert schema.fields['id'].numpy_dtype is np.int64
    assert schema.fields['string'].numpy_dtype is np.str_
    assert schema.fields['int_fixed_size_list'].shape == (None,)


def test_partitioned_write_and_pieces(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    schema = Unischema('P', [
        UnischemaField('part', np.int64, (), ScalarCodec(), False),
        UnischemaField('value', np.float64, (), ScalarCodec(), False),
    ])
    rows = [{'part': i % 3, 'value': float(i)} for i in range(30)]
    write_petastorm_dataset(url, schema, rows, rows_per_row_group=5, partition_by=['part'])
    pieces = load_row_groups(url)
    assert len(pieces) == 6  # 3 partitions x 10 rows / 5-per-rg
    parts = {p.partition_keys.get('part') for p in pieces}
    assert parts == {0, 1, 2}
    # partition column is NOT in the physical files
    some_file = pieces[0].path
    pf = pq.ParquetFile(some_file)
    assert 'part' not in pf.schema_arrow.names


def test_materialize_empty_dataset_raises(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    with pytest.raises(PetastormMetadataError):
        with materialize_dataset(url, _small_schema()):
            pass


def test_filesystem_resolver_schemes(tmp_path):
    fs_local = FilesystemResolver('file://' + str(tmp_path))
    assert fs_local.get_dataset_path() == str(tmp_path)
    from petastorm_tpu.errors import PetastormTpuError
    with pytest.raises(PetastormTpuError):
        FilesystemResolver(str(tmp_path))  # scheme-less rejected
    with pytest.raises(PetastormTpuError):
        FilesystemResolver('ftp://host/x')


def test_resolver_picklable(tmp_path):
    import pickle
    resolver = FilesystemResolver('file://' + str(tmp_path))
    restored = pickle.loads(pickle.dumps(resolver))
    assert restored.get_dataset_path() == str(tmp_path)
    factory = resolver.filesystem_factory()
    assert factory() is not None


def test_synthetic_dataset_fixture(synthetic_dataset):
    pieces = load_row_groups(synthetic_dataset.url)
    assert len(pieces) == 10  # 100 rows / 10 per row group
    assert len({p.path for p in pieces}) == 4  # 30 rows per file -> 4 files
    schema = get_schema(synthetic_dataset.url)
    assert 'image_png' in schema.fields


def test_partition_values_with_slash_and_bool(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    schema = Unischema('P', [
        UnischemaField('kind', np.str_, (), ScalarCodec(), False),
        UnischemaField('flag', np.bool_, (), ScalarCodec(), False),
        UnischemaField('value', np.float64, (), ScalarCodec(), False),
    ])
    rows = [{'kind': 'a/b', 'flag': i % 2 == 0, 'value': float(i)} for i in range(8)]
    write_petastorm_dataset(url, schema, rows, rows_per_row_group=4,
                            partition_by=['kind', 'flag'])
    pieces = load_row_groups(url)
    kinds = {p.partition_keys['kind'] for p in pieces}
    flags = {p.partition_keys['flag'] for p in pieces}
    assert kinds == {'a/b'}
    assert flags == {True, False}
    assert all(isinstance(p.partition_keys['flag'], bool) for p in pieces)


def test_materialize_closes_writers_on_body_exception(tmp_path):
    url = path_to_url(tmp_path / 'ds')
    with pytest.raises(RuntimeError, match='boom'):
        with materialize_dataset(url, _small_schema(), rows_per_row_group=5) as w:
            w.write({'id': 1, 'vec': np.zeros(4, dtype=np.float32)})
            raise RuntimeError('boom')
    # the writer was closed: the partial file has a valid footer
    files = [f for f in (tmp_path / 'ds').iterdir() if f.suffix == '.parquet']
    assert files
    pq.ParquetFile(files[0])  # parses footer without error
