#!/usr/bin/env python
"""Headline benchmark: hello_world reader throughput vs the reference.

Reproduces the reference's published benchmark configuration
(docs/benchmarks_tutorial.rst:20-21 -> 709.84 samples/sec): the HelloWorld
schema (README.rst:70-103 — int32 id + 128x256x3 png image + ragged uint8
array), default 3 thread workers, pure-python read path, warmup then measured
cycles. Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO_ROOT)

CACHE_DIR = os.path.join(REPO_ROOT, '.bench_cache', 'hello_world')
BASELINE_SAMPLES_PER_SEC = 709.84  # reference docs/benchmarks_tutorial.rst:20-21
NUM_ROWS = 1000


def _build_dataset(url):
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('HelloWorldSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(), False),
        UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
        UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
    ])
    rng = np.random.default_rng(42)
    write_petastorm_dataset(url, schema, ({
        'id': i,
        'image1': rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
        'array_4d': rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8),
    } for i in range(NUM_ROWS)), rows_per_row_group=100)


def main():
    url = 'file://' + CACHE_DIR
    if not os.path.exists(os.path.join(CACHE_DIR, '_common_metadata')):
        os.makedirs(CACHE_DIR, exist_ok=True)
        _build_dataset(url)

    from petastorm_tpu.tools.throughput import reader_throughput

    result = reader_throughput(url, warmup_cycles=200, measure_cycles=2000,
                               pool_type='thread', workers_count=3,
                               shuffle_row_groups=True, read_method='python')
    print(json.dumps({
        'metric': 'hello_world_reader_throughput',
        'value': round(result.samples_per_second, 2),
        'unit': 'samples/sec',
        'vs_baseline': round(result.samples_per_second / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == '__main__':
    main()
