#!/usr/bin/env python
"""Headline benchmark: hello_world reader throughput vs the reference, plus
the north-star duty-cycle sweep whenever a TPU is reachable.

Reproduces the reference's published benchmark configuration
(docs/benchmarks_tutorial.rst:20-21 -> 709.84 samples/sec): the HelloWorld
schema (README.rst:70-103 — int32 id + 128x256x3 png image + ragged uint8
array), default 3 thread workers, pure-python read path, warmup then measured
cycles.

Output: one JSON line per duty-sweep point (when a TPU is reachable — probed
in a killable subprocess at capture START and END, because a wedged tunnel
hangs TPU client init forever and a TPU may come up mid-capture), then a
``duty_sweep_best`` or ``duty_sweep_skipped`` line, then the headline
``hello_world_reader_throughput`` line LAST (the driver records the stdout
tail; the headline must survive truncation). The headline line embeds a
compact ``duty`` summary so a one-line capture still carries the north-star
number. Successful on-chip sweeps persist to the committed
``BENCH_ONCHIP.json``; a skip line embeds the newest committed on-chip
result, age-stamped, so the chip number survives tunnel outages. The headline
also carries ``value_spin_normalized`` — the rate corrected by each run's
spin probe (host effective-CPU-speed wander, the diagnosed variance source).

Capture hardening (the recorded number must reflect the framework, not the
container): native targets are built before timing, the cached dataset is
rebuilt when its format stamp is stale, one full measured run is discarded as
warmup, and each of the 7 counted runs records its own CPU share
(process-CPU-time / wall) — on this 1-core host a run that lost the core to a
neighbour shows a visibly lower share, and such contended runs are excluded
from the median with the exclusion recorded, instead of silently bimodalizing
the number (BENCH_r04 spread 0.117 came from exactly this).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO_ROOT)

CACHE_DIR = os.path.join(REPO_ROOT, '.bench_cache', 'hello_world')
BASELINE_SAMPLES_PER_SEC = 709.84  # reference docs/benchmarks_tutorial.rst:20-21
NUM_ROWS = 1000
#: committed ledger of successful ON-CHIP duty sweeps: a capture that finds a
#: TPU appends its result here, and every TPU-less capture embeds the newest
#: committed entry (age-stamped) in its skip line — the north-star number
#: stays visible even when the tunnel is down for months of rounds
ONCHIP_PATH = os.path.join(REPO_ROOT, 'BENCH_ONCHIP.json')
# bump when the on-disk layout the writer produces changes (a stale cached
# store would otherwise benchmark an older format forever)
DATASET_FORMAT_STAMP = 'v2-percolumn-compression'

#: ``--compression-sweep`` codecs: every codec the fused kernel decompresses
#: first-party must ride the SAME hello-world-shaped capture, so the per-codec
#: numbers are comparable and a codec that silently fell back to Arrow shows
#: up as a nonzero ``fallback_compression`` counter, not a plausible-looking
#: slow rate
SWEEP_CODECS = ('snappy', 'zstd', 'lz4', 'none')
SWEEP_ROWS = 256
SWEEP_ROWS_PER_GROUP = 64

#: wall-clock budget for the duty sweep subprocess; points stream as they
#: complete, so a deadline hit still records every finished point
DUTY_SWEEP_TIMEOUT_S = int(os.environ.get('PSTPU_BENCH_DUTY_TIMEOUT', '2400'))

#: ``--workload tokens``: zipf-length token store for the padded-vs-packed
#: capture (docs/sequence.md). Zipf(1.6) capped lengths reproduce the LLM
#: pretraining shape — mostly short rows, a heavy tail — which is exactly the
#: regime where naive padding burns compute and packing wins.
TOKENS_ROWS = 4096
TOKENS_ROWS_PER_GROUP = 256
TOKENS_MAX_LEN = 256
TOKENS_PER_BATCH = 256
TOKENS_SLOTS = 8
TOKENS_PADDED_BATCH = 32


def _build_dataset(url, compression='snappy', num_rows=NUM_ROWS,
                   rows_per_row_group=100):
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('HelloWorldSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(), False),
        UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
        UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
    ])
    rng = np.random.default_rng(42)
    write_petastorm_dataset(url, schema, ({
        'id': i,
        'image1': rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
        'array_4d': rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8),
    } for i in range(num_rows)), rows_per_row_group=rows_per_row_group,
        compression=compression)


def _ensure_dataset(url, cache_dir=None, compression='snappy',
                    num_rows=NUM_ROWS, rows_per_row_group=100):
    import shutil
    cache_dir = cache_dir or CACHE_DIR
    # the default (snappy, full-size) store keeps the historical stamp string
    # so a warm cache from earlier rounds survives this parameterization
    stamp = DATASET_FORMAT_STAMP
    if compression != 'snappy' or num_rows != NUM_ROWS:
        stamp = '{}-{}-{}r{}'.format(DATASET_FORMAT_STAMP, compression,
                                     num_rows, rows_per_row_group)
    stamp_path = os.path.join(cache_dir, '.format_stamp')
    fresh = (os.path.exists(os.path.join(cache_dir, '_common_metadata')) and
             os.path.exists(stamp_path) and
             open(stamp_path).read().strip() == stamp)
    if fresh:
        return
    shutil.rmtree(cache_dir, ignore_errors=True)
    os.makedirs(cache_dir, exist_ok=True)
    _build_dataset(url, compression=compression, num_rows=num_rows,
                   rows_per_row_group=rows_per_row_group)
    with open(stamp_path, 'w') as f:
        f.write(stamp)


def _build_token_dataset(url):
    import numpy as np

    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('TokensSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False),
    ])
    rng = np.random.default_rng(1234)
    write_petastorm_dataset(url, schema, ({
        'id': i,
        'tokens': rng.integers(0, 32000,
                               int(min(rng.zipf(1.6), TOKENS_MAX_LEN)),
                               dtype=np.int32),
    } for i in range(TOKENS_ROWS)), rows_per_row_group=TOKENS_ROWS_PER_GROUP)


def _ensure_token_dataset():
    import shutil
    cache_dir = os.path.join(REPO_ROOT, '.bench_cache', 'tokens')
    url = 'file://' + cache_dir
    stamp = 'tokens-v1-zipf1.6-{}r{}'.format(TOKENS_ROWS, TOKENS_ROWS_PER_GROUP)
    stamp_path = os.path.join(cache_dir, '.format_stamp')
    fresh = (os.path.exists(os.path.join(cache_dir, '_common_metadata')) and
             os.path.exists(stamp_path) and
             open(stamp_path).read().strip() == stamp)
    if not fresh:
        shutil.rmtree(cache_dir, ignore_errors=True)
        os.makedirs(cache_dir, exist_ok=True)
        _build_token_dataset(url)
        with open(stamp_path, 'w') as f:
            f.write(stamp)
    return url


def _simulate_compute(dense, hidden=64):
    """Stand-in for the model's per-token forward cost: project every DENSE
    token (pad tokens included — that is precisely what a real model pays on a
    padded batch, and what packing reclaims) through a ``hidden``-wide
    nonlinearity. The cost is deliberately per-dense-token-proportional and
    large enough to dominate host-side loader overhead, mirroring the
    accelerator regime where the compute:input ratio makes padding waste the
    bill that matters."""
    import numpy as np
    y = np.tanh(dense.astype(np.float32)[..., None] *
                np.linspace(0.1, 1.0, hidden, dtype=np.float32))
    return float(y.mean())


def _tokens_section():
    """Padded-vs-packed effective tokens/s on the zipf-length token store.

    Both paths pay the same decode and the same simulated per-dense-token
    compute; *effective* tokens/s divides REAL (non-pad) tokens by the whole
    wall, so padding waste shows up directly as lost rate. Acceptance
    (docs/sequence.md): packed >= 1.5x padded, ``packing_efficiency`` >= 0.85,
    and the packed stream is bit-exact across same-seed runs (the dummy pool
    pins row order; packing itself is deterministic FFD)."""
    import hashlib

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.sequence import (CollateSpec, PackedSequenceLoader,
                                        PadSpec)

    url = _ensure_token_dataset()
    _warm(url)

    def reader():
        return make_reader(url, reader_pool_type='dummy',
                           shuffle_row_groups=True, seed=0)

    def run_padded():
        t0 = time.perf_counter()
        real = 0
        with reader() as r:
            loader = JaxDataLoader(
                r, batch_size=TOKENS_PADDED_BATCH, drop_last=False,
                collate_spec=CollateSpec({'tokens': PadSpec(pad_to=16)}))
            for batch in loader:
                real += int(batch['tokens_lengths'].sum())
                _simulate_compute(batch['tokens'])
            waste = loader.diagnostics['padding_waste_fraction']
        return real / (time.perf_counter() - t0), waste

    def run_packed(digest=None):
        t0 = time.perf_counter()
        real = 0
        with reader() as r:
            loader = PackedSequenceLoader(
                r, tokens_per_batch=TOKENS_PER_BATCH,
                sequence_fields=['tokens'], slots_per_batch=TOKENS_SLOTS,
                pool_rows=512)
            for batch in loader:
                real += int((batch['segment_ids'] > 0).sum())
                _simulate_compute(batch['tokens'])
                if digest is not None:
                    digest.update(batch['tokens'].tobytes())
                    digest.update(batch['segment_ids'].tobytes())
            eff = loader.packing_efficiency
        return real / (time.perf_counter() - t0), eff

    padded_rates, packed_rates = [], []
    waste = eff = None
    for _ in range(3):
        rate, waste = run_padded()
        padded_rates.append(rate)
        rate, eff = run_packed()
        packed_rates.append(rate)

    d1, d2 = hashlib.sha256(), hashlib.sha256()
    run_packed(digest=d1)
    run_packed(digest=d2)

    padded = statistics.median(padded_rates)
    packed = statistics.median(packed_rates)
    section = {
        'metric': 'tokens_effective_throughput',
        'unit': 'real tokens/sec',
        'padded_tokens_per_sec': round(padded, 1),
        'packed_tokens_per_sec': round(packed, 1),
        'packed_vs_padded': round(packed / padded, 3) if padded else None,
        'packing_efficiency': round(eff, 4),
        'padding_waste_fraction': waste,
        'padded_rounds': [round(r, 1) for r in padded_rates],
        'packed_rounds': [round(r, 1) for r in packed_rates],
        'deterministic': d1.hexdigest() == d2.hexdigest(),
        'stream_sha256': d1.hexdigest()[:16],
        'rows': TOKENS_ROWS,
        'tokens_per_batch': TOKENS_PER_BATCH,
        'slots_per_batch': TOKENS_SLOTS,
        'meets_bar': bool(padded and packed / padded >= 1.5 and eff >= 0.85),
    }
    return section


def _prebuild_native():
    """Compile all native targets before timing — a cold first-use build inside
    the measured region once cost the recorded number ~36% (VERDICT r2)."""
    from petastorm_tpu.native import build
    for fn in (build.build, build.build_shm, build.build_img):
        try:
            fn(quiet=True)
        except Exception:  # noqa: BLE001 - bench falls back like the product does
            pass


def _warm(url):
    """One untimed pass: page cache + namedtuple/codec caches."""
    from petastorm_tpu import make_reader
    with make_reader(url, shuffle_row_groups=False, workers_count=3) as reader:
        for _ in reader:
            pass


def _probe_tpu(timeout_s=90):
    """(platform, device_count) of the ambient jax backend, probed in a
    killable subprocess — TPU client init blocks indefinitely when the tunnel
    is down, so the probe must never run in this process. ('none', 0) on
    timeout/failure."""
    import signal
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, '-c',
         'import jax; d = jax.devices(); print(d[0].platform, len(d))'],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)  # pgid == pid (new session)
        except (OSError, ProcessLookupError):
            pass
        proc.wait()
        return 'none', 0
    try:
        platform, count = out.strip().splitlines()[-1].split()
        return platform, int(count)
    except (ValueError, IndexError):
        return 'none', 0


def _stream_duty_sweep(deadline_s, cmd=None):
    """Run ``bench_duty.py --sweep`` in its own session, re-emitting its JSON
    lines as they arrive so a deadline kill still leaves every completed point
    on stdout. Reads the pipe with raw ``os.read`` (a buffered TextIOWrapper
    would hold complete lines where select can't see them) and sends the
    child's stderr to a temp file (an undrained 64 KiB stderr pipe would
    deadlock a chatty TPU runtime mid-sweep). Returns
    (points, error_reason_or_None)."""
    import selectors
    import signal
    import subprocess
    import tempfile

    cmd = cmd or [sys.executable, os.path.join(REPO_ROOT, 'bench_duty.py'), '--sweep']
    points = []
    buf = b''

    def drain(data):
        nonlocal buf
        buf += data
        while b'\n' in buf:
            line, buf = buf.split(b'\n', 1)
            line = line.strip()
            if not line.startswith(b'{'):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get('metric') == 'duty_sweep':
                points.append(rec)
                print(line.decode(), flush=True)

    with tempfile.TemporaryFile() as errf:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=errf,
                                start_new_session=True, cwd=REPO_ROOT)
        fd = proc.stdout.fileno()
        sel = selectors.DefaultSelector()
        sel.register(proc.stdout, selectors.EVENT_READ)
        deadline = time.monotonic() + deadline_s
        timed_out = False
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                timed_out = True
                break
            if not sel.select(timeout=min(remaining, 5.0)):
                if proc.poll() is not None:
                    break
                continue
            data = os.read(fd, 1 << 16)
            if not data:  # EOF
                break
            drain(data)
        sel.close()
        # Kill the child's whole session unconditionally before the salvage
        # read: a grandchild (reader worker, runtime helper) that inherited
        # stdout would otherwise hold the pipe open and block os.read forever
        # after the child itself died without EOF. On a clean EOF exit the
        # group is already gone and the kill is a no-op.
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass
        proc.wait()
        while True:  # salvage points already in the pipe at kill/EOF time
            data = os.read(fd, 1 << 16)
            if not data:
                break
            drain(data)
        proc.stdout.close()
        if timed_out:
            return points, 'deadline ({}s) hit after {} points'.format(
                deadline_s, len(points))
        if proc.returncode != 0:
            errf.seek(0, os.SEEK_END)
            errf.seek(max(0, errf.tell() - 500))
            err_tail = errf.read().decode(errors='replace')
            return points, 'bench_duty exited rc={}: {}'.format(
                proc.returncode, err_tail.strip().replace('\n', ' | '))
    return points, None


def _load_onchip():
    try:
        with open(ONCHIP_PATH) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get('entries'), list):
            return doc
    except (OSError, ValueError):
        pass
    return {'entries': []}


def _record_onchip(summary):
    """Append a successful on-chip sweep to the committed ledger (atomic
    replace; bounded history so the file never grows unboundedly)."""
    import datetime
    doc = _load_onchip()
    entry = dict(summary)
    entry['recorded_utc'] = datetime.datetime.now(
        datetime.timezone.utc).strftime('%Y-%m-%dT%H:%M:%SZ')
    doc['entries'] = (doc['entries'] + [entry])[-20:]
    tmp = ONCHIP_PATH + '.tmp'
    try:
        with open(tmp, 'w') as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write('\n')
        os.replace(tmp, ONCHIP_PATH)
    except OSError as e:
        print(json.dumps({'metric': 'onchip_persist_failed', 'error': str(e)}),
              flush=True)


def _latest_onchip():
    """Newest committed on-chip result, age-stamped relative to now; None when
    the ledger holds no successful sweep yet."""
    import datetime
    entries = _load_onchip()['entries']
    if not entries:
        return None
    last = dict(entries[-1])
    try:
        rec = datetime.datetime.strptime(
            last.get('recorded_utc', ''), '%Y-%m-%dT%H:%M:%SZ').replace(
                tzinfo=datetime.timezone.utc)
        age = datetime.datetime.now(datetime.timezone.utc) - rec
        last['age_days'] = round(age.total_seconds() / 86400, 1)
    except ValueError:
        last['age_days'] = None
    return last


def _duty_section(tpu_seen_early=False):
    """The north-star: duty-cycle sweep on the real chip when one is
    reachable; a recorded, honest skip when the tunnel is down. The probe is
    OPPORTUNISTIC — it already ran once at capture start (``tpu_seen_early``)
    and runs again here at capture end, so a TPU that comes up mid-capture is
    still used — and PERSISTENT: a successful sweep lands in the committed
    ``BENCH_ONCHIP.json``, and a skip embeds the newest committed on-chip
    result, age-stamped. Returns the compact summary embedded in the headline
    line."""
    platform, count = _probe_tpu()
    if (platform != 'tpu' or count < 1) and not tpu_seen_early:
        reason = ('no TPU reachable at capture start or end (ambient backend: '
                  '{}, {} devices; probe runs in a killable subprocess — a '
                  'wedged tunnel times out instead of hanging)'.format(platform, count))
        skip = {'metric': 'duty_sweep_skipped', 'reason': reason}
        last = _latest_onchip()
        if last is not None:
            skip['last_onchip'] = last
        print(json.dumps(skip), flush=True)
        return {k: v for k, v in skip.items() if k != 'metric'} | {'skipped': True}
    points, error = _stream_duty_sweep(DUTY_SWEEP_TIMEOUT_S)
    if not points:
        reason = error or 'sweep produced no points'
        skip = {'metric': 'duty_sweep_skipped', 'reason': reason,
                'device': platform}
        last = _latest_onchip()
        if last is not None:
            skip['last_onchip'] = last
        print(json.dumps(skip), flush=True)
        return {k: v for k, v in skip.items() if k != 'metric'} | {'skipped': True}
    best = min(points, key=lambda p: p['input_stall_fraction'])
    summary = {
        'metric': 'duty_sweep_best',
        'model': best['model'],
        'step_ms': best['step_ms'],
        'input_stall_fraction': best['input_stall_fraction'],
        'duty_cycle': best['duty_cycle'],
        'examples_per_sec': best['examples_per_sec'],
        'points': len(points),
        'meets_bar': best['input_stall_fraction'] <= 0.05,
        'device': platform,
    }
    if error:
        summary['partial'] = error
    print(json.dumps(summary), flush=True)
    result = {k: v for k, v in summary.items() if k != 'metric'}
    _record_onchip(result)
    return result


def _counters():
    from petastorm_tpu import observability as obs
    try:
        return {k: int(v) for k, v in obs.snapshot().get('counters', {}).items()}
    except Exception:  # noqa: BLE001 - telemetry off: sweep still reports rates
        return {}


def _fused_predicate_share(counters):
    """Share of fused batches that ran the in-kernel predicate stage — the
    machine-checkable signal that filtered reads rode the native pushdown
    (row selection + page-stat skipping inside the GIL-released call) rather
    than the decode-everything-then-mask Python path."""
    total = counters.get('fused_batches_total', 0)
    if not total:
        return None
    return round(counters.get('fused_pred_batches_total', 0) / total, 4)


def _compression_sweep_section():
    """Per-codec fused-read capture on a hello-world-shaped store, plus a
    predicate-filtered phase per codec. Two acceptance numbers live here:
    ``fallback_compression`` must stay 0 for every codec (zstd/lz4 chunks fuse
    through the first-party decompressors, no Arrow fallback), and the zstd
    fused rate must sit within ~10% of snappy's (decompression is not the
    bottleneck the codec choice moves). The predicate phase reads with a
    native-pushdown range on ``id`` that matches only the first row group —
    every other page is skippable from its min/max stats, so
    ``pred_pages_skipped`` > 0 proves filtered reads do strictly less decode
    work, not just less collation."""
    import functools

    from petastorm_tpu import make_reader
    from petastorm_tpu.predicates import in_range
    from petastorm_tpu.tools.throughput import reader_throughput

    phases = {}
    for codec in SWEEP_CODECS:
        cache = os.path.join(REPO_ROOT, '.bench_cache', 'sweep_' + codec)
        url = 'file://' + cache
        _ensure_dataset(url, cache_dir=cache, compression=codec,
                        num_rows=SWEEP_ROWS,
                        rows_per_row_group=SWEEP_ROWS_PER_GROUP)
        _warm(url)
        before = _counters()
        rates = []
        for _ in range(3):
            rates.append(reader_throughput(
                url, warmup_cycles=64, measure_cycles=1024, pool_type='thread',
                workers_count=3, shuffle_row_groups=True, read_method='python',
                make_reader_fn=functools.partial(make_reader, seed=0),
            ).samples_per_second)
        after = _counters()

        # filtered phase: only ids 0..SWEEP_ROWS_PER_GROUP-1 survive, i.e.
        # exactly the first row group of the sequential-id store
        predicate = in_range('id', lo=0, hi=SWEEP_ROWS_PER_GROUP - 1)
        pred_before, t0, matched = _counters(), time.perf_counter(), 0
        epochs = 8
        with make_reader(url, shuffle_row_groups=False, workers_count=3,
                         predicate=predicate, num_epochs=epochs) as reader:
            for _ in reader:
                matched += 1
        wall = time.perf_counter() - t0
        pred_after = _counters()

        def delta(key, a=pred_before, b=pred_after):
            return b.get(key, 0) - a.get(key, 0)

        phase = {
            'metric': 'compression_sweep',
            'codec': codec,
            'fused_samples_per_sec': round(statistics.median(rates), 2),
            'rounds': [round(r, 2) for r in rates],
            # any chunk the kernel refused on codec grounds during the
            # unfiltered rounds — the tentpole's headline acceptance is 0
            'fallback_compression': (after.get('fused_fallback_reason:compression', 0) -
                                     before.get('fused_fallback_reason:compression', 0)),
            'fused_batches': (after.get('fused_batches_total', 0) -
                              before.get('fused_batches_total', 0)),
            'predicate': {
                'selected_rows_per_sec': round(matched / wall, 2) if wall else None,
                'rows_matched': matched,
                'rows_expected': SWEEP_ROWS_PER_GROUP * epochs,
                'pred_batches': delta('fused_pred_batches_total'),
                'pred_pages_skipped': delta('fused_pred_pages_skipped_total'),
                'pred_rows_selected': delta('fused_pred_rows_selected'),
                'fallback_predicate': sum(
                    v - pred_before.get(k, 0) for k, v in pred_after.items()
                    if k.startswith('fused_fallback_column:') and k.endswith(':predicate')),
            },
        }
        print(json.dumps(phase), flush=True)
        phases[codec] = {k: v for k, v in phase.items() if k != 'metric'}

    snappy_rate = phases['snappy']['fused_samples_per_sec']
    zstd_rate = phases['zstd']['fused_samples_per_sec']
    summary = {
        'metric': 'compression_sweep_summary',
        'zstd_vs_snappy': round(zstd_rate / snappy_rate, 3) if snappy_rate else None,
        'zstd_within_10pct': bool(snappy_rate and
                                  abs(zstd_rate - snappy_rate) / snappy_rate <= 0.10),
        'fallback_compression_total': sum(p['fallback_compression'] for p in phases.values()),
        'pred_pages_skipped_total': sum(p['predicate']['pred_pages_skipped']
                                        for p in phases.values()),
        'codecs': phases,
    }
    print(json.dumps(summary), flush=True)
    return {k: v for k, v in summary.items() if k != 'metric'}


def _spin_ms(n=6_000_000):
    """Wall time of a fixed CPU-bound loop — a direct probe of the host's
    EFFECTIVE cpu speed at this instant. On this container it measures
    +-8-15% second-scale wander plus a sustained-load decay (burst-credit
    style), which is the diagnosed source of run-to-run bench variance that
    cpu_share (contention) cannot see. Recorded per run for attribution."""
    t0 = time.perf_counter()
    x = 0
    for i in range(n):
        x += i
    return (time.perf_counter() - t0) * 1000


def _spin_normalized(rates, spins):
    """Headline rate corrected for the host's effective CPU speed at each
    run's moment (the diagnosed CPU-wander variance source): every run is
    scaled by its spin probe relative to the capture's median spin —
    ``rate × spin_ms / median(spin_ms)`` — so a run that was slow only
    because the HOST was slow normalizes back up (and a run flattered by a
    burst-credit fast phase normalizes down). Reported NEXT TO the raw
    median, never instead of it: the raw number is the honest observation,
    the normalized one is comparable across rounds."""
    if not rates or len(rates) != len(spins):
        return None
    med_spin = statistics.median(spins)
    if not med_spin:
        return statistics.median(rates)
    return statistics.median([r * s / med_spin for r, s in zip(rates, spins)])


def _select_runs(runs):
    """Outlier-aware capture: ``runs`` is [(samples_per_sec, cpu_share)].
    Two filters, both reported rather than silent:
      1. contention: runs whose CPU share fell >5 points below the
         best-observed share lost the core to a neighbour (BENCH_r04's 0.117
         spread was two such runs ~10% low);
      2. MAD outliers among the clean runs (modified z > 2.5) — the judge-
         prescribed median-of-7-with-MAD remedy for the residual host-speed
         wander the share filter cannot see.
    The median needs >=4 clean runs to use the filters; a capture contended
    throughout reports all runs, honestly. Returns
    (median, spread_of_inliers, spread_all, excluded_contended,
    excluded_outliers)."""
    shares = [s for _, s in runs]
    share_floor = max(shares) - 0.05
    clean = [r for r, s in runs if s >= share_floor]
    excluded = [round(r, 2) for r, s in runs if s < share_floor]
    all_vals = [r for r, _ in runs]
    med_all = statistics.median(all_vals)
    spread_all = (max(all_vals) - min(all_vals)) / med_all if med_all else 0.0
    if len(clean) < 4:
        return med_all, spread_all, spread_all, [], []
    med = statistics.median(clean)
    mad = statistics.median([abs(r - med) for r in clean])
    if mad > 0:  # mad == 0 (identical runs) means NO dispersion, not infinite z
        inliers = [r for r in clean if abs(r - med) / (1.4826 * mad) <= 2.5]
    else:
        inliers = clean
    mad_excluded = [round(r, 2) for r in clean if r not in inliers]
    value = statistics.median(inliers)
    spread = (max(inliers) - min(inliers)) / value if value else 0.0
    return value, spread, spread_all, excluded, mad_excluded


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(description='headline benchmark capture')
    parser.add_argument('--telemetry', choices=('off', 'counters', 'spans'),
                        default=None,
                        help='pipeline telemetry level for the measured runs '
                             '(default: the process default, counters)')
    parser.add_argument('--trace-out', default=None,
                        help='write a Perfetto-loadable Chrome trace of the capture '
                             'here (implies --telemetry spans)')
    parser.add_argument('--chaos', action='store_true',
                        help='inject one deterministic transient worker error per '
                             'measured run (docs/robustness.md): the headline rate '
                             'then includes recovery overhead, and the output '
                             'carries the recovery counters')
    parser.add_argument('--autotune', action='store_true',
                        help='additionally run the closed-loop convergence probe '
                             '(docs/autotune.md): a deliberately mis-configured '
                             'reader (1 worker) once as-is and once under '
                             'autotune=True; the output records both rates and '
                             'the decision trajectory')
    parser.add_argument('--compression', choices=SWEEP_CODECS, default='snappy',
                        help='parquet codec for the headline hello-world store '
                             '(docs/native.md: every listed codec decodes through '
                             'the same fused kernel via the first-party '
                             'decompressors; the store caches per codec)')
    parser.add_argument('--compression-sweep', action='store_true',
                        help='additionally capture the per-codec fused-read sweep '
                             '+ predicate-filtered phase on hello-world-shaped '
                             'stores: one line per codec, then a summary with the '
                             'zstd-vs-snappy ratio and total page-stat skips')
    parser.add_argument('--workload', choices=('hello_world', 'tokens'),
                        default='hello_world',
                        help="'tokens' captures the sequence-plane headline "
                             'instead: padded-vs-packed effective tokens/s on '
                             'a zipf-length token store, with the packing '
                             'efficiency and a same-seed bit-exactness check '
                             '(docs/sequence.md)')
    parser.add_argument('--blackbox-overhead', action='store_true',
                        help='additionally measure the flight-recorder '
                             'overhead guard: the same read with recording '
                             'off (PSTPU_FLIGHT=0) and on, reported against '
                             'the <=2%% budget (docs/observability.md, '
                             '"Flight recorder")')
    parser.add_argument('--protocol-monitor', action='store_true',
                        help='attach the worker-pool protocol conformance monitor '
                             '(docs/protocol.md) to every measured reader: a chaos '
                             'run then also PROVES the recovery followed the '
                             'supervision protocol (any violation aborts the run '
                             'with ProtocolViolation)')
    # parse_known_args: the capture entry point is also invoked as a plain
    # function from tests (bench.main()) where sys.argv belongs to pytest
    args, _unknown = parser.parse_known_args(argv)
    telemetry = args.telemetry
    if args.trace_out and telemetry in (None, 'off', 'counters'):
        telemetry = 'spans'
    if telemetry is not None:
        from petastorm_tpu import observability as obs
        obs.configure(telemetry)

    if args.workload == 'tokens':
        # self-contained capture: its section IS the headline line (printed
        # last, same driver contract as the hello-world capture)
        print(json.dumps(_tokens_section()), flush=True)
        return

    cache_dir = (CACHE_DIR if args.compression == 'snappy'
                 else CACHE_DIR + '_' + args.compression)
    url = 'file://' + cache_dir
    # opportunistic probe AT CAPTURE START: a TPU reachable now but gone by
    # the end of the ~10-minute CPU capture still gets its duty sweep
    early_platform, early_count = _probe_tpu()
    tpu_seen_early = early_platform == 'tpu' and early_count >= 1
    _prebuild_native()
    _ensure_dataset(url, cache_dir=cache_dir, compression=args.compression)
    _warm(url)

    from petastorm_tpu.tools.throughput import reader_throughput

    import functools

    from petastorm_tpu import make_reader

    def one_run():
        """(samples/sec, cpu_share): cpu_share = this process's CPU seconds /
        wall seconds. On the 1-core bench host an uncontended run sits near
        1.0; a neighbour stealing the core shows directly as a lower share.
        seed=0 pins the shuffle order so every run decodes the IDENTICAL row
        sequence — row-group order must not be a variance source. Under
        --chaos each run additionally recovers from one injected transient
        worker error (fresh one-shot state dir per run)."""
        reader_kwargs = {'seed': 0}
        if args.protocol_monitor:
            reader_kwargs['protocol_monitor'] = True
        if args.chaos:
            import tempfile
            from petastorm_tpu import faults
            faults.install(faults.FaultPlan(
                error_items=(0,), error_times=1,
                state_dir=tempfile.mkdtemp(prefix='bench_chaos_')))
            reader_kwargs.update(on_error='skip', max_item_retries=1)
        try:
            wall0, cpu0 = time.perf_counter(), time.process_time()
            r = reader_throughput(url, warmup_cycles=200, measure_cycles=8000,
                                  pool_type='thread', workers_count=3,
                                  shuffle_row_groups=True,
                                  read_method='python',
                                  make_reader_fn=functools.partial(make_reader,
                                                                   **reader_kwargs)
                                  ).samples_per_second
            wall = time.perf_counter() - wall0
        finally:
            if args.chaos:
                from petastorm_tpu import faults
                faults.uninstall()
        return r, (time.process_time() - cpu0) / wall if wall else 0.0

    # One full-length measured run is DISCARDED (allocator/CPU-state warmup on
    # the 1-core container — the r3 capture trended up monotonically without
    # it), then 7 runs are counted with contention- and MAD-outlier-aware
    # filtering; a spin probe per run records the host's effective cpu speed
    # for attribution (docs/benchmarks.md "capture methodology").
    discarded, _ = one_run()
    runs, spins = [], []
    for _ in range(7):
        spins.append(_spin_ms())
        runs.append(one_run())
    value, spread, spread_all, excluded, mad_excluded = _select_runs(runs)
    spin_med = statistics.median(spins)
    value_norm = _spin_normalized([r for r, _ in runs], spins)

    decode_shares = _decode_collate_section()

    compression_sweep = _compression_sweep_section() if args.compression_sweep else None

    autotune = _autotune_section(url, headline_rate=value) if args.autotune else None

    blackbox_overhead = (_blackbox_overhead_section(url)
                         if args.blackbox_overhead else None)

    duty = _duty_section(tpu_seen_early=tpu_seen_early)

    if args.trace_out:
        from petastorm_tpu import observability as obs
        n_events = obs.export_chrome_trace(args.trace_out)
        print(json.dumps({'metric': 'trace_exported', 'path': args.trace_out,
                          'events': n_events}), flush=True)

    print(json.dumps({
        'metric': 'hello_world_reader_throughput',
        'value': round(value, 2),
        'value_spin_normalized': round(value_norm, 2) if value_norm else None,
        'unit': 'samples/sec',
        'vs_baseline': round(value / BASELINE_SAMPLES_PER_SEC, 3),
        'runs': [round(r, 2) for r, _ in runs],
        'cpu_shares': [round(s, 3) for _, s in runs],
        'spin_ms': [round(s, 1) for s in spins],
        'host_speed_spread': round((max(spins) - min(spins)) / spin_med, 4),
        'excluded_contended': excluded,
        'excluded_mad_outliers': mad_excluded,
        'spread': round(spread, 4),
        'spread_all_runs': round(spread_all, 4),
        'discarded_warm_run': round(discarded, 2),
        # the fused-decode success metric, machine-checkable: Python
        # decode+collate busy seconds as a fraction of pool wait across the
        # measured runs (fused native seconds reported alongside — that is
        # where the decode went, not a Python tail)
        'decode_collate_share': (decode_shares or {}).get('decode_collate_share'),
        'fused_decode_share': (decode_shares or {}).get('fused_decode_share'),
        # share of fused batches that ran the in-kernel predicate stage over
        # the whole capture (the sweep's filtered phases are the contributor;
        # an unfiltered-only capture honestly reports 0.0)
        'fused_predicate_share': _fused_predicate_share(_counters()),
        'compression': args.compression,
        'compression_sweep': compression_sweep,
        'duty': duty,
        'autotune': autotune,
        'blackbox_overhead': blackbox_overhead,
        'chaos': _chaos_section() if args.chaos else None,
        # per-batch critical-path attribution over the capture's span trees
        # (spans level only): traced-batch count + the slowest batches with
        # the stage that owned their dispatch-to-delivery latency
        'critical_path': _critical_path_section(telemetry),
    }))


def _autotune_section(url, headline_rate):
    """The closed-loop convergence probe: the hello-world bench with a
    deliberately mis-configured reader (1 worker instead of the hand-tuned 3),
    measured once as-is and once under autotune=True — the controller must
    claw back most of the hand-tuned rate, and the decision trajectory that
    did it is recorded (docs/autotune.md)."""
    import functools

    from petastorm_tpu import make_reader
    from petastorm_tpu.autotune import AutotuneConfig
    from petastorm_tpu.tools.throughput import reader_throughput

    def one(autotune):
        readers = []

        def mk(*a, **k):
            reader = make_reader(*a, seed=0, autotune=autotune, **k)
            readers.append(reader)
            return reader

        rate = reader_throughput(url, warmup_cycles=100, measure_cycles=8000,
                                 pool_type='thread', workers_count=1,
                                 shuffle_row_groups=True, read_method='python',
                                 make_reader_fn=mk).samples_per_second
        return rate, readers

    try:
        mis_rate, _ = one(None)
        cfg = AutotuneConfig(interval_s=0.4, cooldown_s=0.5, stall_threshold=0.1,
                             max_workers=3)
        tuned_rate, readers = one(cfg)
        tuner = readers[-1].autotuner
        decisions = tuner.decision_records() if tuner is not None else []
        workers_final = tuner.proposal().get('workers_count') if tuner else None
    except Exception as e:  # noqa: BLE001 - the probe must never sink the headline capture
        section = {'metric': 'autotune_convergence', 'error': str(e)}
        print(json.dumps(section), flush=True)
        return {'error': str(e)}
    section = {
        'metric': 'autotune_convergence',
        'misconfigured_rate': round(mis_rate, 2),
        'autotuned_rate': round(tuned_rate, 2),
        'recovered_fraction_of_headline': round(tuned_rate / headline_rate, 3)
        if headline_rate else None,
        'speedup_over_misconfigured': round(tuned_rate / mis_rate, 3)
        if mis_rate else None,
        'workers_start': 1,
        'workers_final': workers_final,
        'decisions': decisions,
    }
    print(json.dumps(section), flush=True)
    return {k: v for k, v in section.items() if k != 'metric'}


def _blackbox_overhead_section(url):
    """Flight-recorder overhead guard (docs/observability.md, "Flight
    recorder"): the measured read once with recording structurally off
    (``PSTPU_FLIGHT=0``) and once with the recorder enabled into a throwaway
    run dir. The counters-level recording budget is <=2% — the recorder adds
    one activity-slot ``pack_into`` per stage execution plus a 1 Hz snapshot
    thread, so anything above that is a regression in the hot-path hook."""
    import functools
    import tempfile

    from petastorm_tpu import make_reader
    from petastorm_tpu.observability import blackbox
    from petastorm_tpu.tools.throughput import reader_throughput

    def one():
        return reader_throughput(url, warmup_cycles=100, measure_cycles=4000,
                                 pool_type='thread', workers_count=3,
                                 shuffle_row_groups=True, read_method='python',
                                 make_reader_fn=functools.partial(make_reader,
                                                                  seed=0)
                                 ).samples_per_second

    def phase(runs=3):
        return statistics.median(one() for _ in range(runs))

    prev_env = os.environ.get('PSTPU_FLIGHT')
    try:
        blackbox.disable()
        os.environ['PSTPU_FLIGHT'] = '0'
        rate_off = phase()
        os.environ.pop('PSTPU_FLIGHT', None)
        run_dir = tempfile.mkdtemp(prefix='bench_flight_')
        blackbox.enable('bench', run_dir=run_dir)
        rate_on = phase()
    except Exception as e:  # noqa: BLE001 - the guard must never sink the headline capture
        section = {'metric': 'blackbox_overhead', 'error': str(e)}
        print(json.dumps(section), flush=True)
        return {'error': str(e)}
    finally:
        from petastorm_tpu.observability import blackbox as _bb
        _bb.disable()
        if prev_env is None:
            os.environ.pop('PSTPU_FLIGHT', None)
        else:
            os.environ['PSTPU_FLIGHT'] = prev_env
    overhead = (1.0 - rate_on / rate_off) if rate_off else None
    section = {
        'metric': 'blackbox_overhead',
        'rate_off': round(rate_off, 2),
        'rate_on': round(rate_on, 2),
        'overhead_fraction': round(overhead, 4) if overhead is not None else None,
        'budget_fraction': 0.02,
        'within_budget': (overhead is not None and overhead <= 0.02),
    }
    print(json.dumps(section), flush=True)
    return {k: v for k, v in section.items() if k != 'metric'}


def _decode_collate_section():
    """decode+collate vs pool-wait shares accumulated over the measured runs
    (the default counters-level telemetry is on for every run)."""
    from petastorm_tpu import observability as obs
    try:
        return obs.decode_collate_share(obs.flatten_snapshot(obs.snapshot()))
    except Exception:  # noqa: BLE001 - telemetry off/reset: the headline still prints
        return None


def _critical_path_section(telemetry):
    """The causal-tracing summary block (docs/observability.md): only
    meaningful when the capture ran at spans level."""
    if telemetry != 'spans':
        return None
    from petastorm_tpu import observability as obs
    try:
        return obs.critical_path_summary(top=3)
    except Exception:  # noqa: BLE001 - attribution must never sink the headline
        return None


def _chaos_section():
    """Recovery counters accumulated across the chaos runs (the pools count
    into the process-wide telemetry registry)."""
    from petastorm_tpu import observability as obs
    counters = obs.snapshot().get('counters', {})
    return {k: int(counters.get(k, 0)) for k in
            ('items_requeued', 'items_quarantined', 'worker_restarts')}


if __name__ == '__main__':
    main()
