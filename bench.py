#!/usr/bin/env python
"""Headline benchmark: hello_world reader throughput vs the reference.

Reproduces the reference's published benchmark configuration
(docs/benchmarks_tutorial.rst:20-21 -> 709.84 samples/sec): the HelloWorld
schema (README.rst:70-103 — int32 id + 128x256x3 png image + ragged uint8
array), default 3 thread workers, pure-python read path, warmup then measured
cycles. Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Capture hardening (the number recorded by the driver must reflect the
framework, not cold caches): all three native targets are built BEFORE the
timed region, the cached dataset is rebuilt when its format stamp is stale,
one full pass warms the page cache, and the reported value is the median of
five measured runs, each long enough (~1.5s of reading) that transient host
contention on the 1-core bench container averages out instead of deciding
the number.
"""

from __future__ import annotations

import json
import os
import statistics
import sys

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO_ROOT)

CACHE_DIR = os.path.join(REPO_ROOT, '.bench_cache', 'hello_world')
BASELINE_SAMPLES_PER_SEC = 709.84  # reference docs/benchmarks_tutorial.rst:20-21
NUM_ROWS = 1000
# bump when the on-disk layout the writer produces changes (a stale cached
# store would otherwise benchmark an older format forever)
DATASET_FORMAT_STAMP = 'v2-percolumn-compression'


def _build_dataset(url):
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('HelloWorldSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(), False),
        UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
        UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
    ])
    rng = np.random.default_rng(42)
    write_petastorm_dataset(url, schema, ({
        'id': i,
        'image1': rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
        'array_4d': rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8),
    } for i in range(NUM_ROWS)), rows_per_row_group=100)


def _ensure_dataset(url):
    import shutil
    stamp_path = os.path.join(CACHE_DIR, '.format_stamp')
    fresh = (os.path.exists(os.path.join(CACHE_DIR, '_common_metadata')) and
             os.path.exists(stamp_path) and
             open(stamp_path).read().strip() == DATASET_FORMAT_STAMP)
    if fresh:
        return
    shutil.rmtree(CACHE_DIR, ignore_errors=True)
    os.makedirs(CACHE_DIR, exist_ok=True)
    _build_dataset(url)
    with open(stamp_path, 'w') as f:
        f.write(DATASET_FORMAT_STAMP)


def _prebuild_native():
    """Compile all native targets before timing — a cold first-use build inside
    the measured region once cost the recorded number ~36% (VERDICT r2)."""
    from petastorm_tpu.native import build
    for fn in (build.build, build.build_shm, build.build_img):
        try:
            fn(quiet=True)
        except Exception:  # noqa: BLE001 - bench falls back like the product does
            pass


def _warm(url):
    """One untimed pass: page cache + namedtuple/codec caches."""
    from petastorm_tpu import make_reader
    with make_reader(url, shuffle_row_groups=False, workers_count=3) as reader:
        for _ in reader:
            pass


def main():
    url = 'file://' + CACHE_DIR
    _prebuild_native()
    _ensure_dataset(url)
    _warm(url)

    from petastorm_tpu.tools.throughput import reader_throughput

    def one_run():
        return reader_throughput(url, warmup_cycles=200, measure_cycles=6000,
                                 pool_type='thread', workers_count=3,
                                 shuffle_row_groups=True,
                                 read_method='python').samples_per_second

    # The r3 capture's 5 runs trended UP monotonically (3904..4934, spread
    # 0.23): the single warm pass did not fully settle allocator/alloc-cache/
    # CPU-state warmup on the 1-core container. One full-length measured run
    # is DISCARDED before the 5 that count.
    discarded = one_run()
    runs = [one_run() for _ in range(5)]
    value = statistics.median(runs)
    spread = (max(runs) - min(runs)) / value if value else 0.0
    print(json.dumps({
        'metric': 'hello_world_reader_throughput',
        'value': round(value, 2),
        'unit': 'samples/sec',
        'vs_baseline': round(value / BASELINE_SAMPLES_PER_SEC, 3),
        'runs': [round(r, 2) for r in runs],
        'spread': round(spread, 4),
        'discarded_warm_run': round(discarded, 2),
    }))


if __name__ == '__main__':
    main()
