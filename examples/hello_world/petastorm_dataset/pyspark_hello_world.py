"""Minimal pyspark read of a HelloWorld dataset — ``dataset_as_rdd`` yields an
RDD of decoded row namedtuples, one reader shard per Spark partition.

Parity: reference examples/hello_world/petastorm_dataset/pyspark_hello_world.py.
When pyspark is not installed (this image has no JVM), the example runs against
``petastorm_tpu.test_util.minispark`` — the local engine implementing the
pyspark API slice the adapter consumes — so the code path still executes.
"""

from __future__ import annotations

import argparse


def _spark_session():
    def _mini():
        from petastorm_tpu.test_util import minispark
        minispark.install()
        from pyspark.sql import SparkSession
        return SparkSession.builder.master('local[2]').appName('pstpu-hello').getOrCreate()

    try:
        from pyspark.sql import SparkSession
    except ImportError:
        return _mini()
    try:
        return SparkSession.builder.master('local[2]').appName('pstpu-hello').getOrCreate()
    except Exception as e:  # noqa: BLE001 — e.g. pyspark installed but no JVM
        print('pyspark session failed ({}); falling back to minispark'.format(e))
        return _mini()


def pyspark_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    from petastorm_tpu.spark_utils import dataset_as_rdd

    spark = _spark_session()
    try:
        rdd = dataset_as_rdd(dataset_url, spark, schema_fields=['id', 'image1'])
        first = rdd.first()
        print('An id in the dataset:', first.id)
        print('image1 shape:', first.image1.shape)
        ids = sorted(row.id for row in rdd.collect())
        print('total rows:', len(ids))
        return ids
    finally:
        spark.stop()


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    pyspark_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
