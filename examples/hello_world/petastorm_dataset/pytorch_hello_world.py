"""Read a HelloWorld dataset through the torch DataLoader adapter.

Parity: reference examples/hello_world/petastorm_dataset/pytorch_hello_world.py.
"""

from __future__ import annotations

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.torch_utils import DataLoader


def pytorch_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with DataLoader(make_reader(dataset_url)) as train_loader:
        sample = next(iter(train_loader))
        print(sample['id'])


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
