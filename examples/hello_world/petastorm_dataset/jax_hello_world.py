"""Read a HelloWorld dataset into device-resident ``jax.Array`` batches.

This replaces the reference's tensorflow_hello_world.py as the native ingestion
path: the loader collates rows into fixed-size batches and stages them onto the
default JAX device.
"""

from __future__ import annotations

import argparse

import jax

from petastorm_tpu import make_reader
from petastorm_tpu.jax import JaxDataLoader


def jax_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url, schema_fields=['id', 'image1']) as reader:
        loader = JaxDataLoader(reader, batch_size=4, drop_last=False,
                               to_device=jax.devices()[0])
        for batch in loader:
            print('id batch:', batch['id'], 'image1:', batch['image1'].shape,
                  'on', batch['image1'].device)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
