"""Generate a small HelloWorld petastorm_tpu dataset.

Parity: reference examples/hello_world/petastorm_dataset/generate_petastorm_dataset.py
(HelloWorldSchema also shown in README.rst:70-103). The reference materializes via a
local Spark session; we write directly with the framework's native writer.
"""

from __future__ import annotations

import argparse

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField

HelloWorldSchema = Unischema('HelloWorldSchema', [
    UnischemaField('id', np.int32, (), ScalarCodec(), False),
    UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
    UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
])


def row_generator(x):
    """Returns a single entry in the generated dataset."""
    rng = np.random.default_rng(x)
    return {'id': x,
            'image1': rng.integers(0, 255, dtype=np.uint8, size=(128, 256, 3)),
            'array_4d': rng.integers(0, 255, dtype=np.uint8, size=(4, 128, 30, 3))}


def generate_petastorm_dataset(output_url='file:///tmp/hello_world_dataset', rows_count=10):
    write_petastorm_dataset(output_url, HelloWorldSchema,
                            (row_generator(i) for i in range(rows_count)),
                            row_group_size_mb=256)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--output-url', default='file:///tmp/hello_world_dataset')
    parser.add_argument('--rows-count', type=int, default=10)
    args = parser.parse_args()
    generate_petastorm_dataset(args.output_url, args.rows_count)


if __name__ == '__main__':
    main()
