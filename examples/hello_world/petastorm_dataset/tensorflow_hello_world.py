"""Read a HelloWorld dataset through the TensorFlow ``tf.data`` adapter.

Parity: reference examples/hello_world/petastorm_dataset/tensorflow_hello_world.py,
re-done for TF2 eager. The reference shows two TF1 idioms — a ``tf_tensors``
session pump and a ``make_one_shot_iterator`` over ``make_petastorm_dataset``;
both collapse to plain eager iteration here (docs/migration.md maps the
TF1 recipe, and the queue-size diagnostic op to ``reader.diagnostics``).
"""

from __future__ import annotations

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset


def tensorflow_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url) as reader:
        dataset = make_petastorm_dataset(reader)
        sample = next(iter(dataset))
        print(sample.id)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    tensorflow_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
