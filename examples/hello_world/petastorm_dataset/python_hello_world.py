"""Minimal plain-Python read of a HelloWorld dataset.

Parity: reference examples/hello_world/petastorm_dataset/python_hello_world.py.
"""

from __future__ import annotations

import argparse

from petastorm_tpu import make_reader


def python_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url) as reader:
        for sample in reader:
            print(sample.id)
            print(sample.image1.shape)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    python_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
