"""Generate a plain (non-petastorm) Parquet store — no Unischema metadata.

Parity: reference examples/hello_world/external_dataset/generate_external_dataset.py,
which writes via a Spark DataFrame. Here pyarrow writes the table directly; the point
is the same: the store carries only an Arrow schema, so reading requires
``make_batch_reader`` with schema inference.
"""

from __future__ import annotations

import argparse
from urllib.parse import urlparse

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def generate_external_dataset(output_url='file:///tmp/external_dataset', rows_count=100):
    path = urlparse(output_url).path
    rng = np.random.default_rng(0)
    table = pa.table({
        'id': pa.array(np.arange(rows_count, dtype=np.int64)),
        'value1': pa.array(rng.integers(0, 255, rows_count, dtype=np.int64)),
        'value2': pa.array(rng.random(rows_count)),
    })
    pq.write_to_dataset(table, path, existing_data_behavior='overwrite_or_ignore')


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--output-url', default='file:///tmp/external_dataset')
    parser.add_argument('--rows-count', type=int, default=100)
    args = parser.parse_args()
    generate_external_dataset(args.output_url, args.rows_count)


if __name__ == '__main__':
    main()
