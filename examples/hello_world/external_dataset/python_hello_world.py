"""Read a plain Parquet store with ``make_batch_reader``.

Parity: reference examples/hello_world/external_dataset/python_hello_world.py.
Each iteration yields a namedtuple of column arrays spanning one row group.
"""

from __future__ import annotations

import argparse

from petastorm_tpu import make_batch_reader


def python_hello_world(dataset_url='file:///tmp/external_dataset'):
    with make_batch_reader(dataset_url) as reader:
        for schema_view in reader:
            print('batch of {} rows; ids: {}'.format(len(schema_view.id), schema_view.id[:10]))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    python_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
