"""Read a plain Parquet store through the torch DataLoader adapter, using
``make_batch_reader`` instead of ``make_reader``.

Parity: reference examples/hello_world/external_dataset/pytorch_hello_world.py.
Because the reader is batched, each DataLoader sample is a batch of rows.
"""

from __future__ import annotations

import argparse

from petastorm_tpu import make_batch_reader
from petastorm_tpu.torch_utils import DataLoader


def pytorch_hello_world(dataset_url='file:///tmp/external_dataset'):
    with DataLoader(make_batch_reader(dataset_url)) as train_loader:
        sample = next(iter(train_loader))
        print('id batch: {}'.format(sample['id']))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
