"""Feed a plain Parquet store into fixed-size ``jax.Array`` batches.

The columnar row-group batches from ``make_batch_reader`` are re-chunked by the
loader into fixed ``batch_size`` batches (static shapes — no XLA recompiles).
"""

from __future__ import annotations

import argparse

import jax

from petastorm_tpu import make_batch_reader
from petastorm_tpu.jax import JaxDataLoader


def jax_hello_world(dataset_url='file:///tmp/external_dataset'):
    with make_batch_reader(dataset_url) as reader:
        loader = JaxDataLoader(reader, batch_size=16, to_device=jax.devices()[0])
        for batch in loader:
            print('id:', batch['id'].shape, batch['id'].dtype, 'value2 mean:', batch['value2'].mean())


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
