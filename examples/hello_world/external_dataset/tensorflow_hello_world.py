"""Read a plain Parquet store through the TensorFlow ``tf.data`` adapter,
using ``make_batch_reader`` instead of ``make_reader``.

Parity: reference examples/hello_world/external_dataset/tensorflow_hello_world.py,
re-done for TF2 eager (the reference's ``tf_tensors`` TF1 session pump and
one-shot iterator both collapse to plain eager iteration; see
docs/migration.md). Each element is a batch of rows spanning one row group.
"""

from __future__ import annotations

import argparse

from petastorm_tpu import make_batch_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset


def tensorflow_hello_world(dataset_url='file:///tmp/external_dataset'):
    with make_batch_reader(dataset_url) as reader:
        dataset = make_petastorm_dataset(reader)
        batched_sample = next(iter(dataset))
        print('id batch: {}'.format(batched_sample.id))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    tensorflow_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
