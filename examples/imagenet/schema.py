"""ImageNet dataset schema.

Parity: reference examples/imagenet/schema.py:21-25 — WordNet noun id, synset
text, and a variable-size RGB image stored png-compressed.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

def make_imagenet_schema(image_codec='png', quality=80):
    """ImagenetSchema with a selectable image compression codec — realistic
    ImageNet pipelines are JPEG; the reference schema is PNG."""
    return Unischema('ImagenetSchema', [
        UnischemaField('noun_id', np.str_, (), ScalarCodec(), False),
        UnischemaField('text', np.str_, (), ScalarCodec(), False),
        UnischemaField('image', np.uint8, (None, None, 3),
                       CompressedImageCodec(image_codec, quality=quality), False),
    ])


ImagenetSchema = make_imagenet_schema('png')
