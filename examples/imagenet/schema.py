"""ImageNet dataset schema.

Parity: reference examples/imagenet/schema.py:21-25 — WordNet noun id, synset
text, and a variable-size RGB image stored png-compressed.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema('ImagenetSchema', [
    UnischemaField('noun_id', np.str_, (), ScalarCodec(), False),
    UnischemaField('text', np.str_, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False),
])
