"""Materialize an ImageNet directory tree (or synthetic stand-in) as a dataset.

Parity: reference examples/imagenet/generate_petastorm_imagenet.py — walks
``<root>/<noun_id>/*.jpg``, writing one row per image with the synset noun id and
text. Without a source tree (this environment has no ImageNet), ``--synthetic``
writes deterministic random images for a configurable number of synthetic
synsets, preserving schema and layout so downstream training examples run.
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from examples.imagenet.schema import ImagenetSchema, make_imagenet_schema
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset


def _iter_imagenet_dir(imagenet_root, noun_id_to_text=None):
    import cv2
    for noun_id in sorted(os.listdir(imagenet_root)):
        synset_dir = os.path.join(imagenet_root, noun_id)
        if not os.path.isdir(synset_dir):
            continue
        text = (noun_id_to_text or {}).get(noun_id, noun_id)
        for name in sorted(os.listdir(synset_dir)):
            if not name.lower().endswith(('.jpg', '.jpeg', '.png')):
                continue
            image = cv2.imread(os.path.join(synset_dir, name), cv2.IMREAD_COLOR)
            if image is None:
                continue
            yield {'noun_id': noun_id, 'text': text,
                   'image': cv2.cvtColor(image, cv2.COLOR_BGR2RGB)}


def synthetic_image(rng, h, w):
    """Photo-like synthetic image: smooth gradients + mild noise. Pure noise
    would be a misleading stand-in — PNG encoders pick no row filters for it
    and decode much faster than for real photographs."""
    yy = np.linspace(0, 4 * np.pi, h)[:, None, None]
    xx = np.linspace(0, 4 * np.pi, w)[None, :, None]
    phase = rng.uniform(0, 2 * np.pi, 3)[None, None, :]
    base = np.sin(xx + phase) * 70 + np.cos(yy + phase * 0.5) * 60 + 128
    return np.clip(base + rng.normal(0, 6, (h, w, 3)), 0, 255).astype(np.uint8)


def _iter_synthetic(num_synsets, images_per_synset, seed=0, min_dim=64, max_dim=160):
    rng = np.random.default_rng(seed)
    for s in range(num_synsets):
        noun_id = 'n{:08d}'.format(s)
        for _ in range(images_per_synset):
            h, w = int(rng.integers(min_dim, max_dim)), int(rng.integers(min_dim, max_dim))
            yield {'noun_id': noun_id, 'text': 'synthetic synset {}'.format(s),
                   'image': synthetic_image(rng, h, w)}


def imagenet_directory_to_petastorm_dataset(imagenet_path, output_url,
                                            row_group_size_mb=256,
                                            noun_id_to_text=None):
    write_petastorm_dataset(output_url, ImagenetSchema,
                            _iter_imagenet_dir(imagenet_path, noun_id_to_text),
                            row_group_size_mb=row_group_size_mb)


def generate_synthetic_imagenet(output_url, num_synsets=4, images_per_synset=8,
                                rows_per_row_group=16, seed=0, image_codec='png',
                                min_dim=64, max_dim=160):
    """``image_codec``: 'png' (reference ImagenetSchema parity) or 'jpeg' —
    realistic ImageNet pipelines are JPEG-compressed. ``min_dim/max_dim``
    bound the random image sizes (real ImageNet photos are ~300-600px)."""
    schema = ImagenetSchema if image_codec == 'png' else make_imagenet_schema(image_codec)
    write_petastorm_dataset(output_url, schema,
                            _iter_synthetic(num_synsets, images_per_synset, seed=seed,
                                            min_dim=min_dim, max_dim=max_dim),
                            rows_per_row_group=rows_per_row_group)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--imagenet-path', default=None,
                        help='root of an ImageNet directory tree (<root>/<noun_id>/*.jpg)')
    parser.add_argument('--output-url', default='file:///tmp/imagenet_dataset')
    parser.add_argument('--synthetic', action='store_true',
                        help='write synthetic images instead of reading --imagenet-path')
    parser.add_argument('--num-synsets', type=int, default=4)
    parser.add_argument('--images-per-synset', type=int, default=8)
    args = parser.parse_args()
    if args.synthetic or not args.imagenet_path:
        generate_synthetic_imagenet(args.output_url, args.num_synsets, args.images_per_synset)
    else:
        imagenet_directory_to_petastorm_dataset(args.imagenet_path, args.output_url)


if __name__ == '__main__':
    main()
