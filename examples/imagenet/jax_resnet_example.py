"""ResNet-50 on an ImageNet-Parquet dataset over a TPU mesh — the BASELINE.md
north-star configuration (ImageNet Parquet + shuffle_row_groups + local disk
cache feeding ResNet-50; sharded multi-host reading via cur_shard/shard_count).

Per-host flow: this host's reader consumes the row-group shard derived from
``jax.process_index()``; worker threads decode+resize; the loader collates and
stages global device arrays over the mesh; the pjit-sharded train step runs on
all chips. No inter-host traffic on the data path (share-nothing, like the
reference's reader.py:485-502) — gradient collectives ride ICI via XLA.
"""

from __future__ import annotations

import argparse
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from petastorm_tpu import TransformSpec, make_reader
from petastorm_tpu import ops
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.models import resnet50
from petastorm_tpu.models.train import (create_train_state, make_train_step,
                                        shard_train_state)
from petastorm_tpu.parallel import data_sharding, make_mesh
from petastorm_tpu.unischema import UnischemaField


# per-channel ImageNet stats in 0-255 units (normalization happens on device)
IMAGENET_MEAN = np.array([123.675, 116.28, 103.53], np.float32)
IMAGENET_STD = np.array([58.395, 57.12, 57.375], np.float32)


class _LabelFromNounId(object):
    """Batched transform, module-level (NOT a closure: process pools pickle the
    TransformSpec into spawned workers). Images arrive already resized by the
    decode worker (``image_resize``), so the only work left is the label
    column."""

    def __init__(self, num_classes):
        self.num_classes = num_classes

    def __call__(self, block):
        # crc32, not hash(): labels must agree across hosts/processes
        # (PYTHONHASHSEED randomizes hash() per interpreter)
        labels = np.fromiter(
            (zlib.crc32(str(n).encode()) % self.num_classes for n in block['noun_id']),
            dtype=np.int64, count=len(block['noun_id']))
        return {'image': block['image'], 'label': labels}


def make_transform(image_size, num_classes):
    """Host side: output stays uint8 — 4x fewer bytes over PCIe than the float
    path; cast/normalize/flip run on device inside the train step
    (petastorm_tpu.ops). ``image_resize`` fuses decode+area-resize into one
    GIL-released native call per column (JPEG stores additionally decode at
    ~target resolution via m/8 DCT scaling — most pixels never exist), and the
    remaining transform is batched: no per-row Python anywhere on the image
    path."""
    return TransformSpec(
        _LabelFromNounId(num_classes),
        edit_fields=[
            UnischemaField('image', np.uint8, (image_size, image_size, 3), None, False),
            UnischemaField('label', np.int64, (), None, False)],
        removed_fields=['noun_id', 'text'],
        batched=True,
        image_resize={'image': (image_size, image_size)})


def device_preprocess(images, rng):
    """Fused on-device input ops: random flip + uint8->bf16 normalize."""
    images = ops.random_flip(images, rng)
    return ops.normalize_images(images, IMAGENET_MEAN, IMAGENET_STD,
                                out_dtype=jnp.bfloat16)


def train(dataset_url, batch_size=64, steps=100, image_size=160, num_classes=1000,
          cache_location=None, seed=0):
    mesh = make_mesh(('data',))
    sharding = data_sharding(mesh)

    model = resnet50(num_classes=num_classes, dtype=jnp.bfloat16)
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               jnp.zeros((1, image_size, image_size, 3)))
    cache_kwargs = {}
    if cache_location:
        cache_kwargs = {'cache_type': 'local-disk', 'cache_location': cache_location,
                        'cache_size_limit': 10 << 30, 'cache_row_size_estimate': 200 << 10}

    with mesh:
        state = shard_train_state(state, mesh)
        train_step = make_train_step(preprocess_fn=device_preprocess,
                                     preprocess_seed=seed)
        with make_reader(dataset_url, num_epochs=None, seed=seed,
                         shuffle_row_groups=True,
                         transform_spec=make_transform(image_size, num_classes),
                         cur_shard=jax.process_index(), shard_count=jax.process_count(),
                         **cache_kwargs) as reader:
            loader = JaxDataLoader(reader, batch_size, shuffling_queue_capacity=1024,
                                   seed=seed, to_device=sharding)
            for step, batch in enumerate(loader):
                state, metrics = train_step(state, batch['image'], batch['label'])
                if step % 10 == 0:
                    print('step {}: loss={:.4f}'.format(step, float(metrics['loss'])))
                if step + 1 >= steps:
                    break
    return state


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/imagenet_dataset')
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--image-size', type=int, default=160)
    parser.add_argument('--num-classes', type=int, default=1000)
    parser.add_argument('--cache-location', default=None)
    args = parser.parse_args()
    train(args.dataset_url, args.batch_size, args.steps, args.image_size,
          args.num_classes, args.cache_location)


if __name__ == '__main__':
    main()
