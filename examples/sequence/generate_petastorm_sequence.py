"""Materialize a synthetic timestamped telemetry dataset for the sequence
(NGram + context parallelism) example."""

from __future__ import annotations

import argparse

import numpy as np

from examples.sequence.schema import make_telemetry_schema
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset


def generate_sequence_dataset(output_url, rows=4096, feature_dim=64,
                              rows_per_row_group=256, seed=0):
    """Smooth AR(1)-style feature drift + per-row noise: windows carry real
    temporal structure, so sequence models have something to learn."""
    schema = make_telemetry_schema(feature_dim)
    rng = np.random.default_rng(seed)

    def rows_iter():
        state = rng.standard_normal(feature_dim).astype(np.float32)
        for i in range(rows):
            state = 0.9 * state + 0.1 * rng.standard_normal(feature_dim).astype(np.float32)
            yield {'timestamp': i,
                   'features': state + 0.05 * rng.standard_normal(feature_dim).astype(np.float32),
                   'sensor_id': int(i % 8)}

    write_petastorm_dataset(output_url, schema, rows_iter(),
                            rows_per_row_group=rows_per_row_group)
    return schema


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--output-url', default='file:///tmp/sequence_dataset')
    parser.add_argument('--rows', type=int, default=4096)
    parser.add_argument('--feature-dim', type=int, default=64)
    args = parser.parse_args()
    generate_sequence_dataset(args.output_url, args.rows, args.feature_dim)


if __name__ == '__main__':
    main()
