"""Long-context training end-to-end: columnar NGram windows feeding a
ring-attention sequence transformer over a ('data','seq') mesh.

The full TPU-native long-context stack in one script:

  make_reader(output='columnar', ngram=...)   zero-per-row-Python window
      |                                       assembly in the decode workers
  JaxDataLoader + stack_ngram_time_axis       [B, T, F] time-major batches
      |
  NamedSharding(mesh, P('data', 'seq'))       batch dp-sharded, sequence
      |                                       context-sharded
  SequenceTransformer(ring attention)         exact attention, k/v shards
      |                                       rotate the ICI ring (ppermute)
  make_train_step                             dp gradients psum'd by XLA

Per pod host, ``cur_shard=jax.process_index()`` keeps the data path
share-nothing exactly like every other reader in the framework.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from examples.sequence.schema import TelemetrySchema
from petastorm_tpu import make_reader
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.jax.loader import stack_ngram_time_axis
from petastorm_tpu.models import make_sequence_transformer
from petastorm_tpu.models.train import (create_train_state, make_train_step,
                                        shard_train_state)
from petastorm_tpu.ngram import NGram
from petastorm_tpu.parallel import make_mesh


def train(dataset_url, steps=50, batch_size=16, window=8, seq_axis_size=None,
          num_classes=8, seed=0, context='ring'):
    feature_dim = TelemetrySchema.fields['features'].shape[0]
    n = len(jax.devices())
    seq_size = seq_axis_size or (2 if n % 2 == 0 else 1)
    mesh = make_mesh(('data', 'seq'), axis_shapes=(-1, seq_size))
    if batch_size % (n // seq_size) or window % seq_size:
        raise ValueError('batch_size must divide the data axis and window the seq axis')

    fields = {i: [TelemetrySchema.fields['timestamp'],
                  TelemetrySchema.fields['features'],
                  TelemetrySchema.fields['sensor_id']] for i in range(window)}
    ngram = NGram(fields, delta_threshold=1,
                  timestamp_field=TelemetrySchema.fields['timestamp'])

    model = make_sequence_transformer(num_classes=num_classes, mesh=mesh,
                                      context_parallelism=context)
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               jnp.zeros((batch_size, window, feature_dim)))
    batch_sharding = NamedSharding(mesh, P('data', 'seq', None))

    with mesh:
        state = shard_train_state(state, mesh)
        step = make_train_step(donate=False)
        with make_reader(dataset_url, output='columnar', ngram=ngram,
                         shuffle_row_groups=True, seed=seed, num_epochs=None,
                         cur_shard=jax.process_index(),
                         shard_count=jax.process_count()) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size, seed=seed)
            it = iter(loader)
            for i in range(steps):
                stacked = stack_ngram_time_axis(next(it))
                x = jax.device_put(stacked['features'], batch_sharding)
                # task: predict the window's sensor at t=0 (structure is real:
                # the AR(1) features drift per sensor stream)
                labels = jnp.asarray(np.asarray(stacked['sensor_id'][:, 0]) % num_classes)
                state, metrics = step(state, x, labels)
                if i % 10 == 0:
                    print('step {}: loss={:.4f}'.format(i, float(metrics['loss'])))
    return state


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/sequence_dataset')
    parser.add_argument('--steps', type=int, default=50)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--window', type=int, default=8)
    parser.add_argument('--context', choices=('ring', 'ulysses'), default='ring',
                        help='context-parallel attention strategy (docs/parallelism.md)')
    args = parser.parse_args()
    train(args.dataset_url, args.steps, args.batch_size, args.window,
          context=args.context)


if __name__ == '__main__':
    main()
