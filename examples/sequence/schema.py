"""Timestamped telemetry schema — the NGram/long-context example dataset.

The reference's examples stop at images (hello_world/mnist/imagenet); its NGram
feature has no example. This schema is the shape NGram was built for
(reference ngram.py:20-125): timestamp-ordered sensor rows windowed into
fixed-length sequences.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField


def make_telemetry_schema(feature_dim=64):
    return Unischema('TelemetrySchema', [
        UnischemaField('timestamp', np.int64, (), ScalarCodec(), False),
        UnischemaField('features', np.float32, (feature_dim,), NdarrayCodec(), False),
        UnischemaField('sensor_id', np.int32, (), ScalarCodec(), False),
    ])


TelemetrySchema = make_telemetry_schema()
