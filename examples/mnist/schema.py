"""MNIST dataset schema.

Parity: reference examples/mnist/schema.py — a 28x28 uint8 image stored via
NdarrayCodec (as the reference does) plus an int64 label. The png image path is
exercised by the hello_world and imagenet examples.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

MnistSchema = Unischema('MnistSchema', [
    UnischemaField('idx', np.int64, (), ScalarCodec(), False),
    UnischemaField('digit', np.int64, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (28, 28), NdarrayCodec(), False),
])
