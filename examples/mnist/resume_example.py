"""Crash-safe MNIST training: joint model + data-position checkpointing.

The reference cannot resume a read mid-epoch (its own §5 gap). Here the FULL
training position checkpoints atomically-enough for real jobs: the flax train
state goes through orbax (the JAX-native checkpointer, async-safe, versioned)
and the loader's read position (`JaxDataLoader.state_dict()` — reader
position + buffered rows + shuffle RNG) rides next to it. A restart resumes
BOTH: no replayed epochs, no silently skipped rows, and with a fixed seed (and
a deterministic-order pool — see ``train_with_checkpointing``) the resumed
stream replays bitwise.

Run:  python examples/mnist/resume_example.py --dataset-url file:///tmp/mnist \
          --checkpoint-dir /tmp/mnist_ckpt --total-steps 200
Kill it anywhere; re-run the same command and it continues where it stopped.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys

import jax
import jax.numpy as jnp

try:
    from examples.mnist.jax_example import TRANSFORM
except ImportError:
    # run as a script: the repo root is not on sys.path, and an unrelated
    # site-packages 'examples' package may already have won the name
    sys.modules.pop('examples', None)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from examples.mnist.jax_example import TRANSFORM
from petastorm_tpu import make_reader
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.models import MnistCNN
from petastorm_tpu.models.train import create_train_state, make_train_step

LOADER_STATE_FILE = 'loader_state.pkl'


def _save(checkpoint_dir, step, state, loader_state):
    import orbax.checkpoint as ocp

    path = os.path.join(checkpoint_dir, 'step_{:08d}'.format(step))
    if os.path.isdir(path) and not os.path.exists(os.path.join(path, 'DONE')):
        # leftover of a crash INSIDE a previous save of this very step: without
        # this sweep orbax would refuse the existing destination forever and
        # the job could never recover past the step it died on
        import shutil
        shutil.rmtree(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, 'train_state'), state)
    ckptr.close()  # block until the async finalize (tmp-dir rename) completes
    with open(os.path.join(path, LOADER_STATE_FILE), 'wb') as f:
        pickle.dump(loader_state, f)
    # the marker makes the checkpoint visible only once COMPLETE (a crash
    # mid-save leaves no half checkpoint to resume from)
    with open(os.path.join(path, 'DONE'), 'w') as f:
        f.write(str(step))


def _latest(checkpoint_dir):
    if not os.path.isdir(checkpoint_dir):
        return None
    done = [d for d in os.listdir(checkpoint_dir)
            if d.startswith('step_') and
            os.path.exists(os.path.join(checkpoint_dir, d, 'DONE'))]
    if not done:
        return None
    return os.path.join(checkpoint_dir, max(done))


def _restore(path, template_state):
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(path, 'train_state'), template_state)
    with open(os.path.join(path, LOADER_STATE_FILE), 'rb') as f:
        loader_state = pickle.load(f)
    return state, loader_state


def train_with_checkpointing(dataset_url, checkpoint_dir, total_steps=100,
                             checkpoint_every=25, batch_size=32, lr=0.05, seed=0,
                             reader_pool_type='thread'):
    """Train to ``total_steps``, checkpointing every ``checkpoint_every``;
    automatically resumes from the latest complete checkpoint in
    ``checkpoint_dir``. Returns the final train state.

    Replay semantics: resume never loses or double-counts a DELIVERED row
    (the loader state carries buffered rows exactly). Bitwise-identical
    replay of the post-resume stream additionally needs a deterministic
    delivery ORDER — ``reader_pool_type='dummy'`` (or 1 worker); with a
    multi-worker pool, row-group arrival order is scheduling-dependent."""
    model = MnistCNN()
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               jnp.zeros((1, 28, 28)), learning_rate=lr)
    train_step = make_train_step()

    loader_state = None
    latest = _latest(checkpoint_dir)
    if latest is not None:
        state, loader_state = _restore(latest, state)
        print('resumed from {} (step {})'.format(latest, int(state.step)))
    if int(state.step) >= total_steps:
        return state

    reader = make_reader(
        dataset_url + '/train', num_epochs=None, seed=seed,
        transform_spec=TRANSFORM, reader_pool_type=reader_pool_type,
        resume_state=None if loader_state is None else loader_state['reader'])
    with JaxDataLoader(reader, batch_size, shuffling_queue_capacity=256, seed=seed,
                       to_device=jax.devices()[0],
                       resume_state=loader_state) as loader:
        for batch in loader:
            state, metrics = train_step(state, batch['image'], batch['digit'])
            step = int(state.step)
            if step % checkpoint_every == 0 or step >= total_steps:
                # state_dict BEFORE touching the next batch: the saved position
                # is exactly "everything up to and including this step's batch"
                _save(checkpoint_dir, step, jax.device_get(state), loader.state_dict())
                print('step {}: loss={:.4f} (checkpointed)'.format(
                    step, float(metrics['loss'])))
            if step >= total_steps:
                break
    return state


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_dataset')
    parser.add_argument('--checkpoint-dir', default='/tmp/mnist_ckpt')
    parser.add_argument('--total-steps', type=int, default=100)
    parser.add_argument('--checkpoint-every', type=int, default=25)
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--lr', type=float, default=0.05)
    parser.add_argument('--seed', type=int, default=0)
    args = parser.parse_args()
    train_with_checkpointing(args.dataset_url, args.checkpoint_dir,
                             args.total_steps, args.checkpoint_every,
                             args.batch_size, lr=args.lr, seed=args.seed)


if __name__ == '__main__':
    main()
