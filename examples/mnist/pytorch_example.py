"""Train a torch model on a petastorm_tpu MNIST dataset.

Parity: reference examples/mnist/pytorch_example.py — same DataLoader +
TransformSpec pattern, feeding the framework's reader into a torch training loop.
"""

from __future__ import annotations

import argparse

import numpy as np

from examples.mnist.schema import MnistSchema  # noqa: F401
from petastorm_tpu import TransformSpec, make_reader
from petastorm_tpu.torch_utils import DataLoader
from petastorm_tpu.unischema import UnischemaField


def _transform_row(row):
    image = (row['image'].astype(np.float32) / 255.0 - 0.1307) / 0.3081
    return {'image': image, 'digit': row['digit']}


TRANSFORM = TransformSpec(
    _transform_row,
    edit_fields=[UnischemaField('image', np.float32, (28, 28), None, False)],
    removed_fields=['idx'])


def train_and_test(dataset_url, batch_size=32, epochs=1, lr=0.01, seed=0):
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    torch.manual_seed(seed)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
            self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
            self.fc1 = nn.Linear(320, 50)
            self.fc2 = nn.Linear(50, 10)

        def forward(self, x):
            x = F.relu(F.max_pool2d(self.conv1(x), 2))
            x = F.relu(F.max_pool2d(self.conv2(x), 2))
            x = x.view(-1, 320)
            x = F.relu(self.fc1(x))
            return F.log_softmax(self.fc2(x), dim=1)

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(), lr=lr, momentum=0.5)

    for epoch in range(epochs):
        model.train()
        with DataLoader(make_reader(dataset_url + '/train', num_epochs=1, seed=seed,
                                         transform_spec=TRANSFORM),
                             batch_size=batch_size) as train_loader:
            for step, batch in enumerate(train_loader):
                data = batch['image'].unsqueeze(1)
                optimizer.zero_grad()
                loss = F.nll_loss(model(data), batch['digit'])
                loss.backward()
                optimizer.step()
                if step % 20 == 0:
                    print('epoch {} step {}: loss={:.4f}'.format(epoch, step, loss.item()))

        model.eval()
        correct = total = 0
        with DataLoader(make_reader(dataset_url + '/test', num_epochs=1,
                                         transform_spec=TRANSFORM),
                             batch_size=batch_size) as test_loader:
            with torch.no_grad():
                for batch in test_loader:
                    pred = model(batch['image'].unsqueeze(1)).argmax(dim=1)
                    correct += int((pred == batch['digit']).sum())
                    total += int(batch['digit'].shape[0])
        print('epoch {}: test accuracy {}/{} = {:.3f}'.format(
            epoch, correct, total, correct / max(total, 1)))
    return model


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_dataset')
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--lr', type=float, default=0.01)
    args = parser.parse_args()
    train_and_test(args.dataset_url, args.batch_size, args.epochs, args.lr)


if __name__ == '__main__':
    main()
