"""Train a small convnet on a petastorm_tpu MNIST dataset — the flagship
end-to-end example (reference examples/mnist/pytorch_example.py, re-done JAX-first).

The reader decodes on host worker threads; a TransformSpec normalizes images on
the workers (off the accelerator's critical path); the JaxDataLoader collates
fixed-size batches and stages them to the device; the jitted train step runs the
model. ``--num-shards`` demonstrates per-host share-nothing sharding.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from examples.mnist.schema import MnistSchema  # noqa: F401  (schema of the dataset read below)
from petastorm_tpu import TransformSpec, make_reader
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.models import MnistCNN
from petastorm_tpu.models.train import create_train_state, make_eval_step, make_train_step
from petastorm_tpu.unischema import UnischemaField


def _transform_row(row):
    # normalization with the reference's MNIST mean/std (pytorch_example.py:26-34)
    image = (row['image'].astype(np.float32) / 255.0 - 0.1307) / 0.3081
    return {'image': image, 'digit': row['digit']}


TRANSFORM = TransformSpec(
    _transform_row,
    edit_fields=[UnischemaField('image', np.float32, (28, 28), None, False)],
    removed_fields=['idx'])


def train_and_test(dataset_url, batch_size=32, epochs=1, lr=0.05, seed=0,
                   cur_shard=None, shard_count=None):
    model = MnistCNN()
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               jnp.zeros((1, 28, 28)), learning_rate=lr)
    train_step, eval_step = make_train_step(), make_eval_step()

    device = jax.devices()[0]
    for epoch in range(epochs):
        with make_reader(dataset_url + '/train', num_epochs=1, seed=seed,
                         transform_spec=TRANSFORM,
                         cur_shard=cur_shard, shard_count=shard_count) as reader:
            loader = JaxDataLoader(reader, batch_size, shuffling_queue_capacity=256,
                                   seed=seed, to_device=device)
            for step, batch in enumerate(loader):
                state, metrics = train_step(state, batch['image'], batch['digit'])
                if step % 20 == 0:
                    print('epoch {} step {}: loss={:.4f}'.format(
                        epoch, step, float(metrics['loss'])))

        correct = total = 0
        with make_reader(dataset_url + '/test', num_epochs=1,
                         transform_spec=TRANSFORM) as reader:
            loader = JaxDataLoader(reader, batch_size, drop_last=False, to_device=device)
            for batch in loader:
                n = int(batch['digit'].shape[0])
                acc_metrics = eval_step(state, batch['image'], batch['digit'])
                correct += int(round(float(acc_metrics['accuracy']) * n))
                total += n
        print('epoch {}: test accuracy {}/{} = {:.3f}'.format(
            epoch, correct, total, correct / max(total, 1)))
    return state


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_dataset')
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--lr', type=float, default=0.05)
    parser.add_argument('--cur-shard', type=int, default=None)
    parser.add_argument('--shard-count', type=int, default=None)
    args = parser.parse_args()
    train_and_test(args.dataset_url, args.batch_size, args.epochs, args.lr,
                   cur_shard=args.cur_shard, shard_count=args.shard_count)


if __name__ == '__main__':
    main()
