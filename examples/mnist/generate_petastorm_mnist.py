"""Materialize MNIST (or a synthetic stand-in) as a petastorm_tpu dataset.

Parity: reference examples/mnist/generate_petastorm_mnist.py, which downloads
MNIST via torchvision and writes train/test groups. This environment has no
network egress, so the default is a deterministic synthetic digit set with the
same schema and train/test layout; pass ``--mnist-data`` pointing at the raw
IDX files to use real MNIST.
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct

import numpy as np

from examples.mnist.schema import MnistSchema
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset


def _synthetic_mnist(n, seed=0):
    """Deterministic digit-like images: a bright blob per class on noise."""
    rng = np.random.default_rng(seed)
    for i in range(n):
        digit = int(rng.integers(0, 10))
        img = rng.integers(0, 32, (28, 28), dtype=np.uint8)
        r, c = 4 + 2 * (digit // 5), 4 + 2 * (digit % 5)
        img[r:r + 8, c:c + 8] = 200 + digit * 5
        yield digit, img


def _read_idx_images(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, n, rows, cols = struct.unpack('>IIII', f.read(16))
        assert magic == 2051, 'not an IDX image file: {}'.format(path)
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, n = struct.unpack('>II', f.read(8))
        assert magic == 2049, 'not an IDX label file: {}'.format(path)
        return np.frombuffer(f.read(), dtype=np.uint8)


def _real_mnist(data_dir, group):
    prefix = 'train' if group == 'train' else 't10k'
    images = labels = None
    for ext in ('', '.gz'):
        ip = os.path.join(data_dir, '{}-images-idx3-ubyte{}'.format(prefix, ext))
        lp = os.path.join(data_dir, '{}-labels-idx1-ubyte{}'.format(prefix, ext))
        if os.path.exists(ip) and os.path.exists(lp):
            images, labels = _read_idx_images(ip), _read_idx_labels(lp)
            break
    if images is None:
        raise FileNotFoundError('MNIST IDX files for {!r} not found in {}'.format(group, data_dir))
    for digit, img in zip(labels, images):
        yield int(digit), img


def mnist_data_to_petastorm_dataset(output_url, mnist_data=None,
                                    train_rows=1000, test_rows=100,
                                    rows_per_row_group=200):
    for group, n in (('train', train_rows), ('test', test_rows)):
        group_url = output_url.rstrip('/') + '/' + group
        source = (_real_mnist(mnist_data, group) if mnist_data
                  else _synthetic_mnist(n, seed=0 if group == 'train' else 1))
        rows = ({'idx': idx, 'digit': digit, 'image': image}
                for idx, (digit, image) in enumerate(source)
                if mnist_data is not None or idx < n)
        write_petastorm_dataset(group_url, MnistSchema, rows,
                                rows_per_row_group=rows_per_row_group)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--output-url', default='file:///tmp/mnist_dataset')
    parser.add_argument('--mnist-data', default=None,
                        help='directory of raw MNIST IDX files; default: synthetic digits')
    parser.add_argument('--train-rows', type=int, default=1000)
    parser.add_argument('--test-rows', type=int, default=100)
    args = parser.parse_args()
    mnist_data_to_petastorm_dataset(args.output_url, args.mnist_data,
                                    args.train_rows, args.test_rows)


if __name__ == '__main__':
    main()
