"""Train a softmax-regression MNIST model from a petastorm_tpu dataset with
TensorFlow (reference examples/mnist/tf_example.py, re-done for TF2 eager:
the reference fed a TF1 session via ``tf_tensors`` + ``tf.train.batch``; here
``make_petastorm_dataset`` feeds the same model through ``tf.data``).
"""

from __future__ import annotations

import argparse

import numpy as np

from petastorm_tpu import make_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset


def train_and_test(dataset_url, training_iterations=100, batch_size=100,
                   evaluation_interval=50, shuffle_buffer_size=256, seed=0):
    """Train for ``training_iterations`` batches, printing test accuracy every
    ``evaluation_interval`` steps; returns the final accuracy."""
    import tensorflow as tf

    w = tf.Variable(tf.zeros([784, 10]))
    b = tf.Variable(tf.zeros([10]))
    optimizer = tf.keras.optimizers.SGD(learning_rate=0.5)

    @tf.function
    def train_step(images, labels):
        with tf.GradientTape() as tape:
            logits = tf.matmul(images, w) + b
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(labels=labels, logits=logits))
        grads = tape.gradient(loss, [w, b])
        optimizer.apply_gradients(zip(grads, [w, b]))
        return loss

    @tf.function
    def accuracy(images, labels):
        logits = tf.matmul(images, w) + b
        correct = tf.equal(tf.argmax(logits, 1), labels)
        return tf.reduce_mean(tf.cast(correct, tf.float32))

    def _as_batch(row_batch):
        images = tf.cast(tf.reshape(row_batch.image, [-1, 784]), tf.float32) / 255.0
        labels = tf.cast(row_batch.digit, tf.int64)
        return images, labels

    final_accuracy = 0.0
    with make_reader(dataset_url + '/train', num_epochs=None, seed=seed) as train_reader:
        train_ds = (make_petastorm_dataset(train_reader,
                                           shuffle_buffer_size=shuffle_buffer_size, seed=seed)
                    .batch(batch_size)
                    .take(training_iterations))
        for step, row_batch in enumerate(train_ds):
            images, labels = _as_batch(row_batch)
            loss = train_step(images, labels)
            if (step + 1) % evaluation_interval == 0 or step + 1 == training_iterations:
                with make_reader(dataset_url + '/test', num_epochs=1) as test_reader:
                    test_ds = make_petastorm_dataset(test_reader).batch(batch_size)
                    accs, weights = [], []
                    for test_batch in test_ds:
                        t_images, t_labels = _as_batch(test_batch)
                        accs.append(float(accuracy(t_images, t_labels)))
                        weights.append(int(t_labels.shape[0]))
                final_accuracy = float(np.average(accs, weights=weights))
                print('step {}: loss={:.4f} test accuracy={:.3f}'.format(
                    step + 1, float(loss), final_accuracy))
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_dataset')
    parser.add_argument('--training-iterations', type=int, default=100)
    parser.add_argument('--batch-size', type=int, default=100)
    parser.add_argument('--evaluation-interval', type=int, default=50)
    args = parser.parse_args()
    train_and_test(args.dataset_url, args.training_iterations, args.batch_size,
                   args.evaluation_interval)


if __name__ == '__main__':
    main()
