#!/usr/bin/env python
"""Shared-reader-service benchmark: decode once, serve many (docs/serve.md).

Measures, on the hello-world bench dataset (the same store ``bench.py``
times):

* **aggregate multi-consumer throughput** — K consumer PROCESSES attached to
  one serve daemon (one shared decode) vs K independent single-job readers
  running concurrently (K private decodes). The serve win is decode
  deduplication: the independent fleet pays K full decode pipelines for the
  same bytes.
* **single-tenant overhead** — one served consumer vs one plain in-process
  reader, same settings.
* **zero-copy delivery** — one process-pool consumer with the copying
  shm deserialize vs ``zero_copy=True`` borrowed views into the ring
  (docs/native.md, "Zero-copy views and slot lifetimes").

Consumers are real processes (spawned with this file as the entry point —
row/batch assembly must not share a GIL), reading columnar blocks (the TPU
hot path: ``JaxDataLoader`` consumes blocks; per-row Python would measure the
consumer, not the serving). Each consumer reports its own steady-state rate;
an aggregate is total rows / max wall time across the overlapping window.

Output: one JSON line per phase, then the ``serve_bench`` headline line LAST
(committed to ``BENCH_r08.json`` by the capture flow).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO_ROOT)

ROWS_PER_CONSUMER = 3000
WARMUP_ROWS = 600
DEFAULT_K = 2


def _consumer_main(argv):
    """Entry point of one consumer process: read columnar blocks and print a
    JSON result line. ``--serve DIR`` attaches through the daemon; otherwise
    a plain private reader is built."""
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--url', required=True)
    parser.add_argument('--serve', default=None)
    parser.add_argument('--pool', default=None,
                        help="reader_pool_type for the private reader "
                             "('process' exercises the shm transport)")
    parser.add_argument('--zero-copy', action='store_true',
                        help='deliver batches as views into the shm ring '
                             '(process pool only)')
    parser.add_argument('--rows', type=int, default=ROWS_PER_CONSUMER)
    parser.add_argument('--warmup-rows', type=int, default=WARMUP_ROWS)
    args = parser.parse_args(argv)

    from petastorm_tpu import make_reader
    kwargs = dict(output='columnar', num_epochs=None, seed=0, workers_count=3)
    if args.serve:
        kwargs['serve'] = args.serve
    if args.pool:
        kwargs['reader_pool_type'] = args.pool
    if args.zero_copy:
        kwargs['zero_copy'] = True
    rows = 0
    warmed = 0
    t0 = None
    reader = make_reader(args.url, **kwargs)
    try:
        for block in reader:
            n = len(block[0])
            if warmed < args.warmup_rows:
                warmed += n
                if warmed >= args.warmup_rows:
                    t0 = time.perf_counter()
                continue
            rows += n
            if rows >= args.rows:
                break
        elapsed = time.perf_counter() - t0
    finally:
        reader.stop()
        reader.join()
    print(json.dumps({'rows': rows, 'elapsed_s': round(elapsed, 4),
                      'rate': round(rows / elapsed, 2)}), flush=True)
    return 0


def _spawn_consumer(url, serve=None, rows=None, pool=None, zero_copy=False):
    argv = [sys.executable, os.path.abspath(__file__), '--consumer',
            '--url', url, '--rows', str(rows or ROWS_PER_CONSUMER),
            '--warmup-rows', str(WARMUP_ROWS)]
    if serve:
        argv += ['--serve', serve]
    if pool:
        argv += ['--pool', pool]
    if zero_copy:
        argv += ['--zero-copy']
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get('PYTHONPATH', ''))
    return subprocess.Popen(argv, stdout=subprocess.PIPE, text=True, env=env,
                            cwd=REPO_ROOT)


def _run_fleet(url, k, serve=None, timeout_s=600, pool=None, zero_copy=False):
    """K concurrent consumer processes; returns (per-consumer results,
    aggregate samples/s over the overlapping window)."""
    t0 = time.perf_counter()
    procs = [_spawn_consumer(url, serve=serve, pool=pool, zero_copy=zero_copy)
             for _ in range(k)]
    results = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout_s)
        if p.returncode != 0:
            raise RuntimeError('consumer failed rc={}'.format(p.returncode))
        results.append(json.loads(out.strip().splitlines()[-1]))
    wall = time.perf_counter() - t0
    total_rows = sum(r['rows'] for r in results)
    # aggregate over the shared window: the slowest consumer's span bounds it
    agg = total_rows / max(r['elapsed_s'] for r in results)
    return results, round(agg, 2), round(wall, 2)


def _with_daemon(url, service_dir, fn):
    """Run ``fn`` with a serve daemon up for ``service_dir``; always shuts the
    daemon down after."""
    from petastorm_tpu.serve.client import connect_service
    conn = connect_service(service_dir, spawn_args={'pool_type': 'thread',
                                                    'workers_count': 3})
    conn.close()
    try:
        return fn()
    finally:
        try:
            conn = connect_service(service_dir, timeout_s=5)
            conn.send({'op': 'shutdown'})
            conn.recv()
            conn.close()
        except Exception:  # noqa: BLE001 - daemon already gone is fine
            pass


def main(argv=None):
    global ROWS_PER_CONSUMER, WARMUP_ROWS
    import argparse
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument('--consumers', type=int, default=None,
                        help='measure ONE fleet size instead of the default '
                             'K=2..3 sweep')
    parser.add_argument('--rows', type=int, default=ROWS_PER_CONSUMER)
    parser.add_argument('--warmup-rows', type=int, default=WARMUP_ROWS)
    parser.add_argument('--url', default=None,
                        help='measure this dataset instead of the hello-world '
                             'bench store (smoke tests use a tiny one)')
    parser.add_argument('--rounds', type=int, default=3,
                        help='rounds per single-reader phase (the median is '
                             'reported; smoke tests pass 1)')
    args, _unknown = parser.parse_known_args(argv)
    ks = [args.consumers] if args.consumers else [2, 3]
    ROWS_PER_CONSUMER = args.rows
    WARMUP_ROWS = args.warmup_rows

    from bench import CACHE_DIR, _ensure_dataset, _prebuild_native, _spin_ms
    if args.url:
        url = args.url
    else:
        url = 'file://' + CACHE_DIR
        _prebuild_native()
        _ensure_dataset(url)

    spin = _spin_ms()

    # 1) single plain reader (in-process baseline). Median of 3: this rate is
    # the denominator of single_tenant_ratio and swings ±15% run-to-run on a
    # busy 1-core host, which would whip the ratio around.
    plain_rates = []
    for _round in range(args.rounds):
        _res, rate_p, _ = _run_fleet(url, 1)
        plain_rates.append(rate_p)
    single_rate = statistics.median(plain_rates)
    print(json.dumps({'metric': 'serve_single_plain', 'rate': single_rate,
                      'rounds': plain_rates}), flush=True)

    sweep = {}
    for k in ks:
        # 2) K independent readers, concurrently (collocated-jobs status quo)
        indep_results, indep_agg, indep_wall = _run_fleet(url, k)
        print(json.dumps({'metric': 'serve_independent_fleet', 'consumers': k,
                          'aggregate': indep_agg, 'wall_s': indep_wall,
                          'per_consumer': [r['rate'] for r in indep_results]}),
              flush=True)

        # 3) K served consumers on one daemon (one shared decode)
        service_dir = tempfile.mkdtemp(prefix='pstpu-serve-bench-')
        served_results, served_agg, served_wall = _with_daemon(
            url, service_dir, lambda: _run_fleet(url, k, serve=service_dir))
        print(json.dumps({'metric': 'serve_shared_fleet', 'consumers': k,
                          'aggregate': served_agg, 'wall_s': served_wall,
                          'per_consumer': [r['rate'] for r in served_results]}),
              flush=True)
        sweep[k] = {'independent_aggregate': indep_agg,
                    'served_aggregate': served_agg,
                    'served_vs_independent': round(served_agg / indep_agg, 3)
                    if indep_agg else None}

    # 4) single served consumer (the serve='auto' overhead number); median of
    # 3 consumer rounds under one daemon, symmetric with the plain baseline
    service_dir2 = tempfile.mkdtemp(prefix='pstpu-serve-bench1-')

    def _served_single_rounds():
        rates = []
        for _round in range(args.rounds):
            _res1, rate_s, _ = _run_fleet(url, 1, serve=service_dir2)
            rates.append(rate_s)
        return rates

    served1_rounds = _with_daemon(url, service_dir2, _served_single_rounds)
    served1_rate = statistics.median(served1_rounds)
    print(json.dumps({'metric': 'serve_single_tenant', 'rate': served1_rate,
                      'rounds': served1_rounds}), flush=True)

    # 5) zero-copy sweep: one process-pool consumer, copying deserialize vs
    # borrowed views into the shm ring (make_reader(..., zero_copy=True)).
    # Median of 3 interleaved rounds: this pair is the headline claim and
    # single-run noise on a 1-core host exceeds the effect size.
    copy_rates, zc_rates = [], []
    for _round in range(args.rounds):
        _resc, rate_c, _ = _run_fleet(url, 1, pool='process')
        copy_rates.append(rate_c)
        _resz, rate_z, _ = _run_fleet(url, 1, pool='process', zero_copy=True)
        zc_rates.append(rate_z)
    pool_copy_rate = statistics.median(copy_rates)
    pool_zc_rate = statistics.median(zc_rates)
    print(json.dumps({'metric': 'pool_copy_single', 'rate': pool_copy_rate,
                      'rounds': copy_rates}), flush=True)
    print(json.dumps({'metric': 'pool_zero_copy_single', 'rate': pool_zc_rate,
                      'rounds': zc_rates}), flush=True)
    zc_ratio = round(pool_zc_rate / pool_copy_rate, 3) if pool_copy_rate else None

    ratios = {k: v['served_vs_independent'] for k, v in sweep.items()}
    headline = {
        'metric': 'serve_bench',
        'unit': 'samples/sec',
        'single_plain_rate': single_rate,
        'sweep': {str(k): v for k, v in sweep.items()},
        'served_vs_independent': ratios.get(2) or next(iter(ratios.values())),
        'best_ratio': max(v for v in ratios.values() if v is not None),
        'meets_bar': any(v is not None and v >= 1.5 for v in ratios.values()),
        'single_served_rate': served1_rate,
        'single_tenant_ratio': round(served1_rate / single_rate, 3) if single_rate else None,
        'pool_copy_rate': pool_copy_rate,
        'pool_zero_copy_rate': pool_zc_rate,
        'zero_copy_ratio': zc_ratio,
        'spin_ms': round(spin, 1),
        'host_cores': os.cpu_count(),
        'note': ('aggregate = total rows / slowest consumer span. This host '
                 'has ONE core and ~2GB/s effective memory bandwidth: the '
                 'serve transport (one blob write per batch, ~7ms/14MB) '
                 'shares the core with decode (~13ms/batch), bounding the '
                 'K=2 ratio near 2d/(d+s)~1.3 and the single-tenant ratio '
                 'near d/(d+s)~0.65; K=3 clears 1.5x because the dedup '
                 'saves two decodes against one copy. On multi-core hosts '
                 'the copy overlaps with decode and both ratios rise. '
                 'zero_copy_ratio ~1.0 on THIS dataset is expected: its '
                 '~14MB image batches spill to the COW-mapped blob plane, '
                 'which both modes view-deliver; zero_copy eliminates the '
                 'per-message copy only for ring-resident batches (and now '
                 'lifetime-tracks the blob views either way). Single-reader '
                 'phases report the median of 3 rounds; fleet phases are '
                 'single-shot and swing ~±15% run-to-run on this host.'),
    }
    print(json.dumps(headline), flush=True)
    return 0


if __name__ == '__main__':
    if '--consumer' in sys.argv:
        argv = [a for a in sys.argv[1:] if a != '--consumer']
        sys.exit(_consumer_main(argv))
    sys.exit(main())
